"""ArbitraryStorage — SWC-124 write to attacker-controlled slot
(reference analysis/module/modules/arbitrary_write.py:79)."""

import logging

from mythril_tpu.analysis.module.base import DetectionModule, EntryPoint
from mythril_tpu.analysis.potential_issues import (
    PotentialIssue,
    get_potential_issues_annotation,
)
from mythril_tpu.analysis.swc_data import WRITE_TO_ARBITRARY_STORAGE
from mythril_tpu.smt import symbol_factory
from mythril_tpu.smt.solver.frontend import UnsatError
from mythril_tpu.support.model import get_model

log = logging.getLogger(__name__)


class ArbitraryStorage(DetectionModule):
    name = "arbitrary_storage_write"
    swc_id = WRITE_TO_ARBITRARY_STORAGE
    description = "Caller can write to arbitrary storage locations."
    entry_point = EntryPoint.CALLBACK
    pre_hooks = ["SSTORE"]

    def _analyze_state(self, state):
        write_slot = state.mstate.stack[-1]
        if not write_slot.symbolic:
            return []
        # can the slot be forced to an arbitrary probe value?
        probe = symbol_factory.BitVecVal(324345425435, 256)
        constraints = [write_slot == probe]
        try:
            get_model(
                state.world_state.constraints.get_all_constraints() + constraints
            )
        except UnsatError:
            return []
        except Exception:
            return []
        potential_issue = PotentialIssue(
            contract=state.environment.active_account.contract_name,
            function_name=state.environment.active_function_name,
            address=state.get_current_instruction().address,
            swc_id=WRITE_TO_ARBITRARY_STORAGE,
            title="Write to an arbitrary storage location",
            severity="High",
            bytecode=state.environment.code.bytecode,
            description_head="The caller can write to arbitrary storage locations.",
            description_tail=(
                "It is possible to write to arbitrary storage locations. By "
                "modifying the values of storage variables, attackers may "
                "bypass security controls or manipulate the business logic of "
                "the smart contract."
            ),
            constraints=constraints,
            detector=self,
        )
        get_potential_issues_annotation(state).potential_issues.append(
            potential_issue
        )
        return []

"""UncheckedRetval — SWC-104 call return value never constrained
(reference analysis/module/modules/unchecked_retval.py:146)."""

import logging

from mythril_tpu.analysis.module.base import DetectionModule, EntryPoint
from mythril_tpu.analysis.report import Issue
from mythril_tpu.analysis.solver import get_transaction_sequence
from mythril_tpu.analysis.swc_data import UNCHECKED_RET_VAL
from mythril_tpu.laser.state.annotation import StateAnnotation
from mythril_tpu.smt.solver.frontend import SolverTimeOutException, UnsatError

log = logging.getLogger(__name__)


class UncheckedRetvalAnnotation(StateAnnotation):
    def __init__(self):
        self.retvals = []  # [{"address": pc, "retval": BitVec}]

    def clone(self):
        dup = UncheckedRetvalAnnotation()
        dup.retvals = list(self.retvals)
        return dup


def _get_annotation(state) -> UncheckedRetvalAnnotation:
    for annotation in state.annotations:
        if isinstance(annotation, UncheckedRetvalAnnotation):
            return annotation
    annotation = UncheckedRetvalAnnotation()
    state.annotate(annotation)
    return annotation


class UncheckedRetval(DetectionModule):
    name = "unchecked_retval"
    swc_id = UNCHECKED_RET_VAL
    description = "Return value of an external call is not checked."
    entry_point = EntryPoint.CALLBACK
    pre_hooks = ["RETURN", "STOP"]
    post_hooks = ["CALL", "DELEGATECALL", "STATICCALL", "CALLCODE"]
    # RETURN/STOP only read recorded retvals; no issue without a call
    trigger_opcodes = ["CALL", "DELEGATECALL", "STATICCALL", "CALLCODE"]

    def _analyze_state(self, state):
        annotation = _get_annotation(state)
        if not self.is_prehook:
            # post-call: remember the pushed return value
            if state.mstate.stack:
                retval = state.mstate.stack[-1]
                if retval.symbolic:
                    annotation.retvals.append(
                        {"address": state.mstate.pc - 1, "retval": retval}
                    )
            return []
        # RETURN/STOP: a retval is "unchecked" if the path never constrained it
        issues = []
        for retval_record in annotation.retvals:
            retval = retval_record["retval"]
            try:
                # can the call have failed (retval == 0) on this very path?
                transaction_sequence = get_transaction_sequence(
                    state,
                    state.world_state.constraints + [retval == 0],
                )
                # and also succeeded? if both, nothing ever checked it
                get_transaction_sequence(
                    state,
                    state.world_state.constraints + [retval == 1],
                )
            except (UnsatError, SolverTimeOutException):
                continue
            except Exception:
                continue
            issues.append(
                Issue(
                    contract=state.environment.active_account.contract_name,
                    function_name=state.environment.active_function_name,
                    address=retval_record["address"],
                    swc_id=UNCHECKED_RET_VAL,
                    title="Unchecked return value from external call.",
                    severity="Medium",
                    bytecode=state.environment.code.bytecode,
                    description_head=(
                        "The return value of a message call is not checked."
                    ),
                    description_tail=(
                        "External calls return a boolean value. If the callee "
                        "halts with an exception, 'false' is returned and "
                        "execution continues in the caller. The caller should "
                        "check whether an exception happened and react "
                        "accordingly to avoid unexpected behavior. For example "
                        "it is often desirable to wrap external calls in "
                        "require() so the transaction is reverted if the call "
                        "fails."
                    ),
                    transaction_sequence=transaction_sequence,
                )
            )
        return issues

"""AccidentallyKillable — SWC-106 unprotected SELFDESTRUCT
(reference analysis/module/modules/suicide.py:125).

Issues are confirmed immediately via get_transaction_sequence (the reference
does NOT route this module through PotentialIssue — suicide.py:70-95) so a
SELFDESTRUCT reached during the creation transaction is still reported even
though creation txs ending in SELFDESTRUCT never reach
check_potential_issues (svm gating on transaction.return_data)."""

import logging
from typing import List

from mythril_tpu.analysis import solver
from mythril_tpu.analysis.issue_annotation import IssueAnnotation
from mythril_tpu.analysis.module.base import DetectionModule, EntryPoint
from mythril_tpu.analysis.report import Issue
from mythril_tpu.analysis.swc_data import UNPROTECTED_SELFDESTRUCT
from mythril_tpu.laser.transaction.models import ContractCreationTransaction
from mythril_tpu.laser.transaction.symbolic import ACTORS
from mythril_tpu.smt import And
from mythril_tpu.smt.solver.frontend import SolverTimeOutException, UnsatError

log = logging.getLogger(__name__)

DESCRIPTION_HEAD = "Any sender can cause the contract to self-destruct."
DESCRIPTION_TAIL = (
    "Any sender can trigger execution of the SELFDESTRUCT instruction to "
    "destroy this contract account and withdraw its balance to an arbitrary "
    "address. Review the transaction trace generated for this issue and "
    "make sure that appropriate security controls are in place to prevent "
    "unrestricted access."
)


class AccidentallyKillable(DetectionModule):
    name = "accidentally_killable"
    swc_id = UNPROTECTED_SELFDESTRUCT
    description = DESCRIPTION_HEAD
    entry_point = EntryPoint.CALLBACK
    pre_hooks = ["SELFDESTRUCT"]

    def _analyze_state(self, state) -> List[Issue]:
        log.debug(
            "SELFDESTRUCT in function %s",
            state.environment.active_function_name,
        )
        instruction = state.get_current_instruction()
        to = state.mstate.stack[-1]

        attacker_constraints = []
        for tx in state.world_state.transaction_sequence:
            if not isinstance(tx, ContractCreationTransaction):
                attacker_constraints.append(
                    And(tx.caller == ACTORS.attacker, tx.caller == tx.origin)
                )

        try:
            try:
                # strongest variant: attacker also receives the funds
                constraints = (
                    list(state.world_state.constraints)
                    + [to == ACTORS.attacker]
                    + attacker_constraints
                )
                transaction_sequence = solver.get_transaction_sequence(
                    state, constraints
                )
                description_tail = (
                    DESCRIPTION_TAIL
                    + " The attacker controls the beneficiary address."
                )
            except UnsatError:
                constraints = (
                    list(state.world_state.constraints) + attacker_constraints
                )
                transaction_sequence = solver.get_transaction_sequence(
                    state, constraints
                )
                description_tail = DESCRIPTION_TAIL
        except (UnsatError, SolverTimeOutException):
            log.debug("no model found for SELFDESTRUCT reachability")
            return []

        issue = Issue(
            contract=state.environment.active_account.contract_name,
            function_name=state.environment.active_function_name,
            address=instruction.address,
            swc_id=UNPROTECTED_SELFDESTRUCT,
            title="Unprotected Selfdestruct",
            severity="High",
            bytecode=state.environment.code.bytecode,
            description_head=DESCRIPTION_HEAD,
            description_tail=description_tail,
            transaction_sequence=transaction_sequence,
            gas_used=(state.mstate.min_gas_used, state.mstate.max_gas_used),
        )
        state.annotate(
            IssueAnnotation(
                conditions=[And(*constraints)], issue=issue, detector=self
            )
        )
        return [issue]

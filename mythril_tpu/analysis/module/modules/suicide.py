"""AccidentallyKillable — SWC-106 unprotected SELFDESTRUCT
(reference analysis/module/modules/suicide.py:125)."""

import logging

from mythril_tpu.analysis.module.base import DetectionModule, EntryPoint
from mythril_tpu.analysis.potential_issues import (
    PotentialIssue,
    get_potential_issues_annotation,
)
from mythril_tpu.analysis.swc_data import UNPROTECTED_SELFDESTRUCT
from mythril_tpu.laser.transaction.symbolic import ACTORS
from mythril_tpu.smt.solver.frontend import UnsatError
from mythril_tpu.support.model import get_model

log = logging.getLogger(__name__)

DESCRIPTION_HEAD = "Any sender can cause the contract to self-destruct."
DESCRIPTION_TAIL = (
    "Any sender can trigger execution of the SELFDESTRUCT instruction to "
    "destroy this contract account and withdraw its balance to an arbitrary "
    "address. Review the transaction trace generated for this issue and "
    "make sure that appropriate security controls are in place to prevent "
    "unrestricted access."
)


class AccidentallyKillable(DetectionModule):
    name = "accidentally_killable"
    swc_id = UNPROTECTED_SELFDESTRUCT
    description = DESCRIPTION_HEAD
    entry_point = EntryPoint.CALLBACK
    pre_hooks = ["SELFDESTRUCT"]

    def _analyze_state(self, state):
        instruction = state.get_current_instruction()
        to = state.mstate.stack[-1]

        attacker_constraints = []
        for tx in state.world_state.transaction_sequence:
            if not isinstance(tx.caller, int) and tx.caller.symbolic:
                attacker_constraints.append(tx.caller == ACTORS.attacker)

        try:
            # strongest variant: attacker also receives the funds
            constraints = attacker_constraints + [to == ACTORS.attacker]
            get_model(
                state.world_state.constraints.get_all_constraints() + constraints
            )
            description_tail = (
                DESCRIPTION_TAIL
                + " The attacker controls the beneficiary address."
            )
        except UnsatError:
            try:
                constraints = attacker_constraints
                get_model(
                    state.world_state.constraints.get_all_constraints()
                    + constraints
                )
                description_tail = DESCRIPTION_TAIL
            except UnsatError:
                return []
        except Exception:
            return []

        potential_issue = PotentialIssue(
            contract=state.environment.active_account.contract_name,
            function_name=state.environment.active_function_name,
            address=instruction.address,
            swc_id=UNPROTECTED_SELFDESTRUCT,
            title="Unprotected Selfdestruct",
            severity="High",
            bytecode=state.environment.code.bytecode,
            description_head=DESCRIPTION_HEAD,
            description_tail=description_tail,
            constraints=constraints,
            detector=self,
        )
        get_potential_issues_annotation(state).potential_issues.append(
            potential_issue
        )
        return []

"""ArbitraryJump — SWC-127 attacker-controlled jump destination
(reference analysis/module/modules/arbitrary_jump.py:113)."""

import logging

from mythril_tpu.analysis.module.base import DetectionModule, EntryPoint
from mythril_tpu.analysis.report import Issue
from mythril_tpu.analysis.solver import get_transaction_sequence
from mythril_tpu.analysis.swc_data import ARBITRARY_JUMP
from mythril_tpu.smt.solver.frontend import SolverTimeOutException, UnsatError

log = logging.getLogger(__name__)


class ArbitraryJump(DetectionModule):
    name = "arbitrary_jump"
    swc_id = ARBITRARY_JUMP
    description = "Caller can redirect execution to arbitrary bytecode locations."
    entry_point = EntryPoint.CALLBACK
    pre_hooks = ["JUMP", "JUMPI"]
    # fires (and solves) ONLY on a symbolic jump destination — a cone the
    # static CFG fully resolved (every target a push constant) cannot
    # trigger it, so inert-cone analysis may ignore this module's hooks
    symbolic_jump_only = True

    def _analyze_state(self, state):
        jump_dest = state.mstate.stack[-1]
        if not jump_dest.symbolic:
            return []
        try:
            transaction_sequence = get_transaction_sequence(
                state, state.world_state.constraints
            )
        except (UnsatError, SolverTimeOutException):
            return []
        except Exception:
            return []
        return [
            Issue(
                contract=state.environment.active_account.contract_name,
                function_name=state.environment.active_function_name,
                address=state.get_current_instruction().address,
                swc_id=ARBITRARY_JUMP,
                title="Jump to an arbitrary instruction",
                severity="High",
                bytecode=state.environment.code.bytecode,
                description_head="The caller can redirect execution to arbitrary bytecode locations.",
                description_tail=(
                    "It is possible to redirect the control flow to arbitrary "
                    "locations in the code. This may allow an attacker to "
                    "bypass security controls or manipulate the business logic "
                    "of the smart contract. Avoid using low-level-operations "
                    "and assembly to prevent this issue."
                ),
                transaction_sequence=transaction_sequence,
            )
        ]

"""RequirementsViolation — SWC-123 callee-reachable revert with caller data
(reference analysis/module/modules/requirements_violation.py:85)."""

import logging

from mythril_tpu.analysis.module.base import DetectionModule, EntryPoint
from mythril_tpu.analysis.report import Issue
from mythril_tpu.analysis.solver import get_transaction_sequence
from mythril_tpu.analysis.swc_data import REQUIREMENT_VIOLATION
from mythril_tpu.smt.solver.frontend import SolverTimeOutException, UnsatError

log = logging.getLogger(__name__)


class RequirementsViolation(DetectionModule):
    name = "requirements_violation"
    swc_id = REQUIREMENT_VIOLATION
    description = "A requirement was violated in a nested call."
    entry_point = EntryPoint.CALLBACK
    pre_hooks = ["REVERT"]

    def _analyze_state(self, state):
        # only flag REVERTs inside called (inner) frames: the caller supplied
        # data that made the callee's require() fail
        inner_frames = sum(
            1 for _tx, snap in state.transaction_stack if snap is not None
        )
        if inner_frames == 0:
            return []
        try:
            transaction_sequence = get_transaction_sequence(
                state, state.world_state.constraints
            )
        except (UnsatError, SolverTimeOutException):
            return []
        except Exception:
            return []
        return [
            Issue(
                contract=state.environment.active_account.contract_name,
                function_name=state.environment.active_function_name,
                address=state.get_current_instruction().address,
                swc_id=REQUIREMENT_VIOLATION,
                title="Requirement Violation",
                severity="Medium",
                bytecode=state.environment.code.bytecode,
                description_head="A requirement was violated in a nested call and the call was reverted as a result.",
                description_tail=(
                    "Make sure valid inputs are provided to the nested call "
                    "(for instance, via passed arguments)."
                ),
                transaction_sequence=transaction_sequence,
            )
        ]

"""TransactionOrderDependence — SWC-114 value transfer racing on storage
(reference analysis/module/modules/transaction_order_dependence.py:48-137).

Taint-annotation mechanism mirroring the reference: post-hooks on
SLOAD/BALANCE annotate the pushed value with the reading transaction's
sender; the annotation rides the engine's BitVec wrappers through any
arithmetic. At a CALL whose transfer value carries the taint, the payout
depends on balance/storage another transaction can change first —
front-running the write changes what the call pays out. (A post-hoc
statespace scan cannot detect this here: read-over-write elimination folds
`SLOAD(slot)` of a just-written slot into the written expression, so no
storage select survives in the value term.)"""

import logging

from mythril_tpu.analysis.module.base import DetectionModule, EntryPoint
from mythril_tpu.analysis.potential_issues import (
    PotentialIssue,
    get_potential_issues_annotation,
)
from mythril_tpu.analysis.swc_data import TX_ORDER_DEPENDENCE
from mythril_tpu.laser.transaction.symbolic import ACTORS
from mythril_tpu.smt import Or, symbol_factory
from mythril_tpu.smt.solver.frontend import UnsatError
from mythril_tpu.support.model import get_model

log = logging.getLogger(__name__)


class BalanceAnnotation:
    def __init__(self, caller):
        self.caller = caller


class StorageAnnotation:
    def __init__(self, caller):
        self.caller = caller


class TxOrderDependence(DetectionModule):
    name = "tx_order_dependence"
    swc_id = TX_ORDER_DEPENDENCE
    description = "The call value depends on balance or storage writable by other transactions."
    entry_point = EntryPoint.CALLBACK
    pre_hooks = ["CALL"]
    post_hooks = ["BALANCE", "SLOAD"]
    # BALANCE/SLOAD only source taint; the issue itself fires at a CALL
    trigger_opcodes = ["CALL"]

    def _analyze_state(self, state):
        if not self.is_prehook:
            # post BALANCE/SLOAD: taint the pushed value with the sender
            if state.mstate.stack:
                value = state.mstate.stack[-1]
                annotation = (
                    BalanceAnnotation
                    if self.current_opcode == "BALANCE"
                    else StorageAnnotation
                )
                if not value.get_annotations(annotation):
                    value.annotate(annotation(state.environment.sender))
            return []

        value = state.mstate.stack[-3]
        # mirror the reference's gate exactly: a caller is harvested only
        # when EXACTLY ONE annotation of that type is present (reference
        # transaction_order_dependence.py appends iff len(annotations) == 1).
        # A value combining two differently-tainted reads (annotation-set
        # union through arithmetic) is suppressed — call_constraint stays
        # False -> UNSAT -> no report, matching the reference's findings.
        callers = []
        for annotation_type in (StorageAnnotation, BalanceAnnotation):
            annotations = value.get_annotations(annotation_type)
            if len(annotations) == 1:
                callers.append(annotations[0].caller)
        if not callers:
            return []
        call_constraint = symbol_factory.Bool(False)
        for caller in callers:
            call_constraint = Or(call_constraint, ACTORS.attacker == caller)
        constraints = [call_constraint]
        try:
            get_model(
                state.world_state.constraints.get_all_constraints()
                + constraints
            )
        except UnsatError:
            return []
        except Exception:
            return []
        potential_issue = PotentialIssue(
            contract=state.environment.active_account.contract_name,
            function_name=state.environment.active_function_name,
            address=state.get_current_instruction().address,
            swc_id=TX_ORDER_DEPENDENCE,
            title="Transaction Order Dependence",
            severity="Medium",
            bytecode=state.environment.code.bytecode,
            description_head=(
                "The value of the call is dependent on balance or "
                "storage write"
            ),
            description_tail=(
                "This can lead to race conditions. An attacker may be "
                "able to run a transaction after our transaction which "
                "can change the value of the call, e.g. by front-running "
                "a storage write that determines the amount paid out."
            ),
            constraints=constraints,
            detector=self,
        )
        get_potential_issues_annotation(state).potential_issues.append(
            potential_issue
        )
        return []

"""TransactionOrderDependence — SWC-114 value transfer racing on storage
(reference analysis/module/modules/transaction_order_dependence.py:140,
POST entry).

Heuristic (mirrors the reference): find CALL ops whose transfer value
depends on a storage read, and SSTORE writes (in other transactions) that
may alias the slot feeding that value — front-running the write changes
what the call pays out."""

import logging

from mythril_tpu.analysis.module.base import DetectionModule, EntryPoint
from mythril_tpu.analysis.report import Issue
from mythril_tpu.analysis.solver import get_transaction_sequence
from mythril_tpu.analysis.swc_data import TX_ORDER_DEPENDENCE
from mythril_tpu.smt import terms as _terms
from mythril_tpu.smt.solver.frontend import SolverTimeOutException, UnsatError

log = logging.getLogger(__name__)


def _storage_reads(term):
    """Base-array storage selects inside a term."""
    reads = []
    for node in _terms.walk_terms([term]):
        if node.op == "select":
            base = node.children[0]
            while base.op == "store":
                base = base.children[0]
            if base.op == "array" and str(base.params[0]).startswith("Storage"):
                reads.append((base.params[0], node.children[1]))
    return reads


class TxOrderDependence(DetectionModule):
    name = "tx_order_dependence"
    swc_id = TX_ORDER_DEPENDENCE
    description = "The call value depends on storage writable by other transactions."
    entry_point = EntryPoint.POST

    def _analyze_statespace(self, statespace) -> list:
        issues = []
        # gather storage-dependent call values and sstore events
        calls = []   # (state, instruction, reads)
        writes = []  # (tx_id, slot_term)
        for node in statespace.nodes.values():
            for state in node.states:
                instruction = state.get_current_instruction()
                if instruction is None:
                    continue
                stack = (
                    state.mstate_stack
                    if hasattr(state, "mstate_stack")
                    else state.mstate.stack
                )
                if instruction.opcode in ("CALL", "CALLCODE") and len(stack) >= 3:
                    value = stack[-3]
                    if value.symbolic:
                        reads = _storage_reads(value.raw)
                        if reads:
                            calls.append((state, instruction, reads))
                elif instruction.opcode == "SSTORE" and len(stack) >= 2:
                    tx = state.transaction
                    writes.append(
                        (tx.id if tx else None, stack[-1].raw)
                    )
        seen = set()
        for state, instruction, reads in calls:
            tx = state.transaction
            tx_id = tx.id if tx else None
            racing = False
            for write_tx, write_slot in writes:
                if write_tx == tx_id:
                    continue  # same transaction cannot be front-run
                for _arr, read_slot in reads:
                    alias = _terms.eq(write_slot, read_slot)
                    if not (alias.is_const and alias.value is False):
                        racing = True
                        break
                if racing:
                    break
            if not racing:
                continue
            key = (
                instruction.address,
                "0x" + state.environment.code.bytecode_hash.hex(),
            )
            if key in seen or key in self.cache:
                continue
            try:
                transaction_sequence = get_transaction_sequence(
                    state, state.constraints
                )
            except (UnsatError, SolverTimeOutException, AttributeError):
                continue
            except Exception:
                continue
            seen.add(key)
            issues.append(
                Issue(
                    contract=state.environment.active_account.contract_name,
                    function_name=state.environment.active_function_name,
                    address=instruction.address,
                    swc_id=TX_ORDER_DEPENDENCE,
                    title="Transaction Order Dependence",
                    severity="Medium",
                    bytecode=state.environment.code.bytecode,
                    description_head=(
                        "The value of the call is dependent on balance or "
                        "storage write"
                    ),
                    description_tail=(
                        "This can lead to race conditions. An attacker may be "
                        "able to run a transaction after our transaction which "
                        "can change the value of the call, e.g. by "
                        "front-running a storage write that determines the "
                        "amount paid out."
                    ),
                    transaction_sequence=transaction_sequence,
                )
            )
        return issues

"""StateChangeAfterCall — SWC-107 state write after external call
(reference analysis/module/modules/state_change_external_calls.py:205)."""

import logging

from mythril_tpu.analysis.module.base import DetectionModule, EntryPoint
from mythril_tpu.analysis.potential_issues import (
    PotentialIssue,
    get_potential_issues_annotation,
)
from mythril_tpu.analysis.swc_data import REENTRANCY
from mythril_tpu.laser.state.annotation import StateAnnotation
from mythril_tpu.smt import UGT, symbol_factory
from mythril_tpu.smt.solver.frontend import UnsatError
from mythril_tpu.support.model import get_model

log = logging.getLogger(__name__)


class CallIssueAnnotation(StateAnnotation):
    def __init__(self, call_address: int, user_defined_address: bool):
        self.call_address = call_address
        self.user_defined_address = user_defined_address

    def clone(self):
        return CallIssueAnnotation(self.call_address, self.user_defined_address)


class StateChangeAfterCall(DetectionModule):
    name = "state_change_external_calls"
    swc_id = REENTRANCY
    description = "State change after an external call."
    entry_point = EntryPoint.CALLBACK
    pre_hooks = ["CALL", "DELEGATECALL", "CALLCODE", "SSTORE", "CREATE",
                 "CREATE2"]

    def _analyze_state(self, state):
        opcode = self.current_opcode
        if opcode in ("CALL", "DELEGATECALL", "CALLCODE"):
            gas = state.mstate.stack[-1]
            to = state.mstate.stack[-2]
            # only calls that can execute code (enough gas) count
            try:
                get_model(
                    state.world_state.constraints.get_all_constraints()
                    + [UGT(gas, symbol_factory.BitVecVal(2300, 256))]
                )
            except UnsatError:
                return []
            except Exception:
                return []
            state.annotate(
                CallIssueAnnotation(
                    call_address=state.get_current_instruction().address,
                    user_defined_address=to.symbolic,
                )
            )
            return []

        # state-changing opcode: flag if any prior external call on this path
        annotations = [
            a for a in state.annotations if isinstance(a, CallIssueAnnotation)
        ]
        if not annotations:
            return []
        annotation = annotations[-1]
        severity = "Medium" if annotation.user_defined_address else "Low"
        address_desc = (
            "a user-defined address" if annotation.user_defined_address
            else "a fixed address"
        )
        potential_issue = PotentialIssue(
            contract=state.environment.active_account.contract_name,
            function_name=state.environment.active_function_name,
            address=state.get_current_instruction().address,
            swc_id=REENTRANCY,
            title="State access after external call",
            severity=severity,
            bytecode=state.environment.code.bytecode,
            description_head=(
                "Write to persistent state following external call"
            ),
            description_tail=(
                f"The contract account state is accessed after an external "
                f"call to {address_desc}. To prevent reentrancy issues, "
                f"consider accessing the state only before the call, "
                f"especially if the callee is untrusted. Alternatively, a "
                f"reentrancy lock can be used to prevent untrusted callees "
                f"from re-entering the contract in an intermediate state."
            ),
            constraints=[],
            detector=self,
        )
        get_potential_issues_annotation(state).potential_issues.append(
            potential_issue
        )
        return []

"""TxOrigin — SWC-115 branch condition tainted by ORIGIN
(reference analysis/module/modules/dependence_on_origin.py:114)."""

import logging
from typing import List

from mythril_tpu.analysis.module.base import DetectionModule, EntryPoint
from mythril_tpu.analysis.report import Issue
from mythril_tpu.analysis.solver import get_transaction_sequence
from mythril_tpu.analysis.swc_data import TX_ORIGIN_USAGE
from mythril_tpu.smt.solver.frontend import SolverTimeOutException, UnsatError

log = logging.getLogger(__name__)


class TxOriginAnnotation:
    """Marker attached to the ORIGIN value (taint via expression annotations)."""


class TxOrigin(DetectionModule):
    name = "tx_origin"
    swc_id = TX_ORIGIN_USAGE
    description = "Control flow depends on tx.origin."
    entry_point = EntryPoint.CALLBACK
    pre_hooks = ["JUMPI"]
    post_hooks = ["ORIGIN"]
    # JUMPI is only a taint OBSERVER: no issue without ORIGIN executing
    trigger_opcodes = ["ORIGIN"]

    def _analyze_state(self, state) -> List[Issue]:
        if self.current_opcode == "ORIGIN":
            # post-hook: annotate the pushed value
            state.mstate.stack[-1].annotate(TxOriginAnnotation())
            return []
        instruction = state.get_current_instruction()
        # JUMPI pre-hook: check the branch condition for the taint marker
        condition = state.mstate.stack[-2]
        if not any(
            isinstance(a, TxOriginAnnotation) for a in condition.annotations
        ):
            return []
        try:
            transaction_sequence = get_transaction_sequence(
                state, state.world_state.constraints
            )
        except (UnsatError, SolverTimeOutException):
            return []
        except Exception:
            return []
        return [
            Issue(
                contract=state.environment.active_account.contract_name,
                function_name=state.environment.active_function_name,
                address=instruction.address,
                swc_id=TX_ORIGIN_USAGE,
                title="Dependence on tx.origin",
                severity="Low",
                bytecode=state.environment.code.bytecode,
                description_head="Use of tx.origin as a part of authorization control.",
                description_tail=(
                    "The tx.origin environment variable has been found to "
                    "influence a control flow decision. Note that using "
                    "tx.origin as a security control might cause a situation "
                    "where a user inadvertently authorizes a smart contract "
                    "to perform an action on their behalf. It is recommended "
                    "to use msg.sender instead."
                ),
                transaction_sequence=transaction_sequence,
            )
        ]

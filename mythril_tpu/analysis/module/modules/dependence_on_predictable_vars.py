"""PredictableVariables — SWC-116/120 branch depends on block env values
(reference analysis/module/modules/dependence_on_predictable_vars.py:196)."""

import logging

from mythril_tpu.analysis.module.base import DetectionModule, EntryPoint
from mythril_tpu.analysis.report import Issue
from mythril_tpu.analysis.solver import get_transaction_sequence
from mythril_tpu.analysis.swc_data import TIMESTAMP_DEPENDENCE, WEAK_RANDOMNESS
from mythril_tpu.smt.solver.frontend import SolverTimeOutException, UnsatError

log = logging.getLogger(__name__)

PREDICTABLE_OPS = ["COINBASE", "GASLIMIT", "TIMESTAMP", "NUMBER",
                   "PREVRANDAO", "BLOCKHASH"]


class PredictableValueAnnotation:
    def __init__(self, operation: str):
        self.operation = operation


class PredictableVariables(DetectionModule):
    name = "predictable_variables"
    swc_id = f"{TIMESTAMP_DEPENDENCE}, {WEAK_RANDOMNESS}"
    description = "Control flow depends on predictable block values."
    entry_point = EntryPoint.CALLBACK
    pre_hooks = ["JUMPI"]
    post_hooks = PREDICTABLE_OPS
    # JUMPI is only a taint OBSERVER: no issue without a predictable-value
    # source opcode executing first
    trigger_opcodes = PREDICTABLE_OPS

    def _analyze_state(self, state):
        if not self.is_prehook:
            # post-hook on env opcode: annotate the pushed value
            if state.mstate.stack:
                state.mstate.stack[-1].annotate(
                    PredictableValueAnnotation(self.current_opcode)
                )
            return []
        condition = state.mstate.stack[-2]
        markers = [
            a for a in condition.annotations
            if isinstance(a, PredictableValueAnnotation)
        ]
        if not markers:
            return []
        operation = markers[0].operation
        try:
            transaction_sequence = get_transaction_sequence(
                state, state.world_state.constraints
            )
        except (UnsatError, SolverTimeOutException):
            return []
        except Exception:
            return []
        swc_id = (
            TIMESTAMP_DEPENDENCE
            if operation == "TIMESTAMP"
            else WEAK_RANDOMNESS
        )
        pretty = operation.lower()
        return [
            Issue(
                contract=state.environment.active_account.contract_name,
                function_name=state.environment.active_function_name,
                address=state.get_current_instruction().address,
                swc_id=swc_id,
                title="Dependence on predictable environment variable",
                severity="Low",
                bytecode=state.environment.code.bytecode,
                description_head=(
                    "A control flow decision is made based on "
                    f"block.{pretty}."
                ),
                description_tail=(
                    f"The block.{pretty} environment variable is used to "
                    "determine a control flow decision. Note that the values "
                    "of variables like coinbase, gaslimit, block number and "
                    "timestamp are predictable and can be manipulated by a "
                    "malicious miner. Also keep in mind that attackers know "
                    "hashes of earlier blocks. Don't use any of those "
                    "environment variables as sources of randomness and be "
                    "aware that use of these variables introduces a certain "
                    "level of trust into miners."
                ),
                transaction_sequence=transaction_sequence,
            )
        ]

from mythril_tpu.analysis.module.base import (  # noqa: F401
    DetectionModule,
    EntryPoint,
)
from mythril_tpu.analysis.module.loader import ModuleLoader  # noqa: F401
from mythril_tpu.analysis.module.util import (  # noqa: F401
    get_detection_module_hooks,
    reset_callback_modules,
)

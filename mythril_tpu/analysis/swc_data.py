"""SWC registry constants (reference mythril/analysis/swc_data.py:67)."""

REENTRANCY = "107"
UNPROTECTED_SELFDESTRUCT = "106"
UNPROTECTED_ETHER_WITHDRAWAL = "105"
UNCHECKED_RET_VAL = "104"
DEPRECATED_FUNCTIONS_USAGE = "111"
DELEGATECALL_TO_UNTRUSTED_CONTRACT = "112"
INTEGER_OVERFLOW_AND_UNDERFLOW = "101"
DOS_WITH_BLOCK_GAS_LIMIT = "128"
TX_ORDER_DEPENDENCE = "114"
TX_ORIGIN_USAGE = "115"
TIMESTAMP_DEPENDENCE = "116"
WEAK_RANDOMNESS = "120"
ASSERT_VIOLATION = "110"
DEFAULT_FUNCTION_VISIBILITY = "100"
MULTIPLE_SENDS = "113"
UNPROTECTED_SUICIDE = "106"
WRITE_TO_ARBITRARY_STORAGE = "124"
ARBITRARY_JUMP = "127"
UNEXPECTED_ETHER_BALANCE = "132"
REQUIREMENT_VIOLATION = "123"

SWC_TO_TITLE = {
    "100": "Function Default Visibility",
    "101": "Integer Overflow and Underflow",
    "102": "Outdated Compiler Version",
    "103": "Floating Pragma",
    "104": "Unchecked Call Return Value",
    "105": "Unprotected Ether Withdrawal",
    "106": "Unprotected SELFDESTRUCT Instruction",
    "107": "Reentrancy",
    "108": "State Variable Default Visibility",
    "109": "Uninitialized Storage Pointer",
    "110": "Assert Violation",
    "111": "Use of Deprecated Solidity Functions",
    "112": "Delegatecall to Untrusted Callee",
    "113": "DoS with Failed Call",
    "114": "Transaction Order Dependence",
    "115": "Authorization through tx.origin",
    "116": "Block values as a proxy for time",
    "117": "Signature Malleability",
    "118": "Incorrect Constructor Name",
    "119": "Shadowing State Variables",
    "120": "Weak Sources of Randomness from Chain Attributes",
    "123": "Requirement Violation",
    "124": "Write to Arbitrary Storage Location",
    "127": "Arbitrary Jump with Function Type Variable",
    "128": "DoS With Block Gas Limit",
    "132": "Unexpected Ether balance",
}

"""Two-phase issue confirmation (reference analysis/potential_issues.py:126).

Modules record PotentialIssues (predicate constraints, no tx model yet) in a
state annotation; at transaction end check_potential_issues re-solves
world_constraints + issue constraints and promotes survivors to Issues with
a concrete transaction sequence."""

import logging
from typing import List

from mythril_tpu.laser.state.annotation import StateAnnotation
from mythril_tpu.smt.solver.frontend import SolverTimeOutException, UnsatError

log = logging.getLogger(__name__)


class PotentialIssue:
    def __init__(
        self,
        contract,
        function_name,
        address,
        swc_id,
        title,
        bytecode,
        detector,
        severity,
        description_head,
        description_tail,
        constraints=None,
    ):
        self.title = title
        self.contract = contract
        self.function_name = function_name
        self.address = address
        self.description_head = description_head
        self.description_tail = description_tail
        self.severity = severity
        self.swc_id = swc_id
        self.bytecode = bytecode
        self.constraints = constraints or []
        self.detector = detector


class PotentialIssuesAnnotation(StateAnnotation):
    def __init__(self):
        self.potential_issues: List[PotentialIssue] = []

    @property
    def search_importance(self):
        return 10 * len(self.potential_issues)

    def clone(self):
        # per-path copy: an issue detected on one branch must be confirmed
        # with THAT branch's world state at its transaction end, so the
        # concretized tx sequence matches the function the issue fired in
        # (reference deep-copies annotations with the state)
        dup = PotentialIssuesAnnotation()
        dup.potential_issues = list(self.potential_issues)
        return dup


def get_potential_issues_annotation(global_state) -> PotentialIssuesAnnotation:
    for annotation in global_state.annotations:
        if isinstance(annotation, PotentialIssuesAnnotation):
            return annotation
    annotation = PotentialIssuesAnnotation()
    global_state.annotate(annotation)
    return annotation


def _detector_cache_key(potential_issue):
    """(address, bytecode hash) — the detector's per-issue dedup key."""
    try:
        from mythril_tpu.utils.keccak import keccak256

        raw = potential_issue.bytecode or b""
        if isinstance(raw, str):
            raw = bytes.fromhex(raw.removeprefix("0x"))
        bytecode_hash = "0x" + keccak256(raw).hex()
    except ValueError:
        bytecode_hash = ""
    return potential_issue.address, bytecode_hash


def check_potential_issues(global_state) -> None:
    """Called at transaction end (engine svm._end_transaction).

    Confirmation is two-stage: all candidate issues' feasibility checks
    (world constraints + issue predicate, a detection-critical verdict) go
    through ONE get_models_batch call first — the batched device fan-out the
    router size-buckets — and only the satisfiable survivors pay the full
    exploit concretization with lexicographic minimization. UNSAT/UNKNOWN
    candidates stay recorded: constraints may become satisfiable after a
    later transaction mutates state (reference potential_issues.py:97-99)."""
    annotation = get_potential_issues_annotation(global_state)
    unsatisfied = []
    candidates = []
    for potential_issue in annotation.potential_issues:
        # per-path annotation copies mean sibling end states each carry the
        # same recorded issue; once one path confirmed it (detector cache
        # hit, keyed like Issue.bytecode_hash), skip re-confirming the rest
        if _detector_cache_key(potential_issue) in potential_issue.detector.cache:
            continue
        candidates.append(potential_issue)

    if len(candidates) > 1:
        # batched pre-filter: one device-routable fan-out over every
        # candidate's feasibility cone. The pre-filter solves a SUBSET of
        # the final constraints (no calldata-size caps yet), so UNSAT here
        # soundly implies the full confirmation is UNSAT too; SAT survivors
        # still get the full minimized solve below (and its model now sits
        # in the model cache).
        from mythril_tpu.service.scheduler import get_scheduler
        from mythril_tpu.support.model import detection_context

        try:
            with detection_context():
                # every candidate's feasibility cone rides the coalescing
                # scheduler: one window flush, one batched router fan-out
                # (crosscheck=None: resolved against the ambient detection
                # context at flush time — inside this `with`)
                outcomes = get_scheduler().solve_batch([
                    (global_state.world_state.constraints
                     + candidate.constraints).get_all_constraints()
                    for candidate in candidates
                ])
        except Exception:
            log.exception("batched issue pre-filter failed; confirming "
                          "candidates one by one")
            outcomes = [("unknown", None)] * len(candidates)
        survivors = []
        for candidate, (status, _model) in zip(candidates, outcomes):
            if status == "unsat":
                unsatisfied.append(candidate)
            else:
                survivors.append(candidate)
        candidates = survivors

    for potential_issue in candidates:
        # re-check the detector cache per candidate: an earlier confirm in
        # THIS loop may have cached the same (address, bytecode) key (two
        # recordings of one issue along a looping path) — without this the
        # duplicate would re-confirm and report twice
        if _detector_cache_key(potential_issue) in potential_issue.detector.cache:
            continue
        try:
            from mythril_tpu.analysis.solver import get_transaction_sequence

            transaction_sequence = get_transaction_sequence(
                global_state,
                global_state.world_state.constraints + potential_issue.constraints,
            )
        except (UnsatError, SolverTimeOutException):
            # keep it: constraints may become satisfiable after a later
            # transaction mutates state (reference potential_issues.py:97-99)
            unsatisfied.append(potential_issue)
            continue
        from mythril_tpu.analysis.report import Issue

        issue = Issue(
            contract=potential_issue.contract,
            function_name=potential_issue.function_name,
            address=potential_issue.address,
            title=potential_issue.title,
            bytecode=potential_issue.bytecode,
            swc_id=potential_issue.swc_id,
            severity=potential_issue.severity,
            description_head=potential_issue.description_head,
            description_tail=potential_issue.description_tail,
            transaction_sequence=transaction_sequence,
        )
        from mythril_tpu.support.args import args

        if args.use_issue_annotations:
            # summaries mode: carry the proof obligation on the state so
            # the summary plugin can re-solve it under substitution
            from mythril_tpu.analysis.issue_annotation import IssueAnnotation
            from mythril_tpu.smt import And

            global_state.annotate(IssueAnnotation(
                conditions=[And(
                    *(list(global_state.world_state.constraints)
                      + list(potential_issue.constraints))
                )],
                issue=issue,
                detector=potential_issue.detector,
            ))
        else:
            potential_issue.detector.issues.append(issue)
            potential_issue.detector.update_cache([issue])
    annotation.potential_issues = unsatisfied

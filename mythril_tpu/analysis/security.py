"""Run POST modules + collect callback issues
(reference analysis/security.py:45)."""

import logging
from typing import List, Optional

from mythril_tpu.analysis.module import EntryPoint, ModuleLoader
from mythril_tpu.analysis.module.util import reset_callback_modules

log = logging.getLogger(__name__)


def retrieve_callback_issues(white_list: Optional[List[str]] = None) -> List:
    issues = []
    for module in ModuleLoader().get_detection_modules(
        entry_point=EntryPoint.CALLBACK, white_list=white_list
    ):
        issues.extend(module.issues)
    reset_callback_modules(module_names=white_list)
    return issues


def fire_lasers(statespace, white_list: Optional[List[str]] = None) -> List:
    """Execute POST modules over the statespace, then gather everything."""
    issues = []
    for module in ModuleLoader().get_detection_modules(
        entry_point=EntryPoint.POST, white_list=white_list
    ):
        log.info("running POST module %s", module.name)
        module.execute(statespace)
        issues.extend(module.issues)
        module.reset_module()
    issues.extend(retrieve_callback_issues(white_list))
    return issues

"""Exploit concretization (reference mythril/analysis/solver.py:257).

get_transaction_sequence turns a SAT path into a concrete attack: solve the
path + issue constraints while minimizing calldata sizes and call values
(reference :217-257), then extract per-transaction concrete inputs from the
model (reference :185-214)."""

import logging
from typing import Dict, List

from mythril_tpu.laser.state.constraints import Constraints
from mythril_tpu.laser.transaction.models import (
    BaseTransaction,
    ContractCreationTransaction,
)
from mythril_tpu.smt import ULE, symbol_factory
from mythril_tpu.smt.solver.frontend import UnsatError  # noqa: F401 (re-export)
from mythril_tpu.support.model import get_model

log = logging.getLogger(__name__)

MAX_CALLDATA_SIZE = 5000


def pretty_print_model(model) -> str:
    lines = []
    for name in sorted(str(d) for d in model.decls()):
        lines.append(f"{name}: {model.assignment.get(name)}")
    return "\n".join(lines)


def get_transaction_sequence(global_state, constraints: Constraints) -> Dict:
    """Solve constraints and concretize the tx sequence; raises UnsatError.

    Runs in a detection context: an UNSAT here is a detection-critical "no
    exploit" verdict (module predicates, potential-issue confirmation), so
    get_model requests the permuted-instance crosscheck by default."""
    from mythril_tpu.support.model import detection_context

    transaction_sequence = global_state.world_state.transaction_sequence

    tx_constraints, minimize = _set_minimisation_constraints(
        transaction_sequence,
        Constraints(list(constraints)),
    )
    with detection_context():
        model = get_model(
            tx_constraints.get_all_constraints()
            if isinstance(tx_constraints, Constraints)
            else tx_constraints,
            minimize=minimize,
        )

    steps = []
    initial_accounts = {}
    for transaction in transaction_sequence:
        concrete = _get_concrete_transaction(model, transaction)
        steps.append(concrete)
    # initial world state snapshot (reference :168-182)
    first_tx = transaction_sequence[0] if transaction_sequence else None
    if first_tx is not None:
        world = (
            first_tx.prev_world_state
            if isinstance(first_tx, ContractCreationTransaction)
            and first_tx.prev_world_state is not None
            else first_tx.world_state
        )
        for address, account in world.accounts.items():
            initial_accounts[f"0x{address:040x}"] = {
                "nonce": account.nonce,
                "code": account.serialised_code,
                "storage": {
                    str(k): str(v) for k, v in account.storage.printable_storage.items()
                },
                "balance": "0x0",
            }
    return {
        "initialState": {"accounts": initial_accounts},
        "steps": steps,
    }


def _get_concrete_transaction(model, transaction: BaseTransaction) -> Dict:
    caller = f"0x{model.eval_int(transaction.caller):040x}"
    value = hex(model.eval_int(transaction.call_value))
    if isinstance(transaction, ContractCreationTransaction):
        from mythril_tpu.disasm.disassembly import _concrete_projection

        address = ""
        input_data = _concrete_projection(transaction.code.bytecode).hex()
    else:
        callee = transaction.callee_account.address
        address = f"0x{model.eval_int(callee):040x}"
        calldata_bytes = transaction.call_data.concrete(model)
        input_data = bytes(
            byte if isinstance(byte, int) else 0 for byte in calldata_bytes
        ).hex()
    return {
        "origin": caller,
        "address": address,
        "input": f"0x{input_data}",
        "value": value,
        "name": getattr(transaction, "contract_name", "") or "unknown",
    }


def _set_minimisation_constraints(transaction_sequence, constraints):
    """Cap + minimize calldata size and value (reference :217-257)."""
    minimize = []
    for transaction in transaction_sequence:
        if transaction.call_data is not None and hasattr(
            transaction.call_data, "calldatasize"
        ):
            size = transaction.call_data.calldatasize
            if size.symbolic:
                constraints.append(
                    ULE(size, symbol_factory.BitVecVal(MAX_CALLDATA_SIZE, 256))
                )
                minimize.append(size)
        if transaction.call_value is not None and transaction.call_value.symbolic:
            minimize.append(transaction.call_value)
    return constraints, tuple(minimize)

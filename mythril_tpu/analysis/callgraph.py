"""Interactive CFG html for `--graph` (reference analysis/callgraph.py:248).

Renders the node/edge statespace with vis.js loaded from CDN (same approach
as the reference's jinja template; self-contained data payload)."""

import json

from mythril_tpu.smt import terms as _terms

_PAGE = """<!DOCTYPE html>
<html>
<head>
<meta charset="utf-8">
<title>mythril_tpu call graph</title>
<script src="https://unpkg.com/vis-network/standalone/umd/vis-network.min.js"></script>
<style>
  body {{ margin: 0; background: #1e1e2e; }}
  #graph {{ width: 100vw; height: 100vh; }}
</style>
</head>
<body>
<div id="graph"></div>
<script>
  const nodes = new vis.DataSet({nodes});
  const edges = new vis.DataSet({edges});
  const container = document.getElementById("graph");
  const options = {{
    nodes: {{ shape: "box", font: {{ face: "monospace", color: "#cdd6f4" }},
             color: {{ background: "#313244", border: "#89b4fa" }} }},
    edges: {{ arrows: "to", color: {{ color: "#9399b2" }} }},
    physics: {{ enabled: {physics} }},
    layout: {{ improvedLayout: true }}
  }};
  new vis.Network(container, {{ nodes, edges }}, options);
</script>
</body>
</html>
"""


def generate_graph(sym, physics: bool = False, phrackify: bool = False) -> str:
    nodes = []
    for node in sym.nodes.values():
        code_lines = []
        for state in node.states[:30]:
            instruction = state.get_current_instruction()
            if instruction is None:
                continue
            if isinstance(instruction.argument, bytes):
                arg = f" 0x{instruction.argument.hex()}"
            elif instruction.argument is not None:
                arg = " <symbolic>"  # deploy-time-patched operand
            else:
                arg = ""
            code_lines.append(f"{instruction.address} {instruction.opcode}{arg}")
        label = f"{node.function_name}\\n" + "\\n".join(code_lines[:16])
        nodes.append({"id": node.uid, "label": label})
    edges = [
        {
            "from": edge.node_from,
            "to": edge.node_to,
            "label": (
                _terms.term_to_str(edge.condition.raw, max_depth=4)
                if edge.condition is not None and hasattr(edge.condition, "raw")
                else ""
            ),
        }
        for edge in sym.edges
    ]
    return _PAGE.format(
        nodes=json.dumps(nodes),
        edges=json.dumps(edges),
        physics="true" if physics else "false",
    )

"""Statespace JSON serialization for `-j` (reference analysis/traceexplore.py:166)."""

from typing import Dict, List

from mythril_tpu.smt import terms as _terms


def get_serializable_statespace(sym) -> Dict:
    nodes: List[Dict] = []
    node_uid_to_index = {}
    for node in sym.nodes.values():
        states = []
        for state in node.states:
            instruction = state.get_current_instruction()
            stack = (
                state.mstate_stack
                if hasattr(state, "mstate_stack")
                else list(state.mstate.stack)
            )
            states.append(
                {
                    "address": instruction.address if instruction else None,
                    "opcode": instruction.opcode if instruction else "END",
                    "stack": [
                        _terms.term_to_str(v.raw, max_depth=6) for v in stack
                    ],
                }
            )
        node_uid_to_index[node.uid] = len(nodes)
        nodes.append(
            {
                "id": node.uid,
                "contract": node.contract_name,
                "function": node.function_name,
                "startAddr": node.start_addr,
                "constraints": [
                    _terms.term_to_str(c.raw, max_depth=6)
                    for c in list(node.constraints)
                ],
                "states": states,
            }
        )
    edges = [
        {
            "from": edge.node_from,
            "to": edge.node_to,
            "type": edge.type.name,
        }
        for edge in sym.edges
    ]
    return {"nodes": nodes, "edges": edges}

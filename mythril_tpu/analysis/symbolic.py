"""SymExecWrapper — builds/configures the engine for one contract
(reference analysis/symbolic.py:334)."""

import copy
import logging
from typing import List, Optional

from mythril_tpu.analysis.module import (
    EntryPoint,
    ModuleLoader,
    get_detection_module_hooks,
)
from mythril_tpu.analysis.ops import Call, get_call_from_state, get_variable
from mythril_tpu.laser.strategy.basic import (
    BreadthFirstSearchStrategy,
    DepthFirstSearchStrategy,
    ReturnRandomNaivelyStrategy,
    ReturnWeightedRandomStrategy,
)
from mythril_tpu.laser.strategy.extensions.bounded_loops import (
    BoundedLoopsStrategy,
)
from mythril_tpu.laser.svm import LaserEVM
from mythril_tpu.laser.transaction.symbolic import ACTORS
from mythril_tpu.smt import symbol_factory
from mythril_tpu.support.args import args

log = logging.getLogger(__name__)


class SymExecWrapper:
    def __init__(
        self,
        contract,
        address,
        strategy: str = "bfs",
        dynloader=None,
        max_depth: int = 128,
        execution_timeout: Optional[int] = None,
        loop_bound: int = 3,
        create_timeout: Optional[int] = None,
        transaction_count: int = 2,
        modules: Optional[List[str]] = None,
        compulsory_statespace: bool = True,
        disable_dependency_pruning: bool = False,
        run_analysis_modules: bool = True,
        custom_modules_directory: str = "",
    ):
        if isinstance(address, str):
            address = symbol_factory.BitVecVal(int(address, 16), 256)
        elif isinstance(address, int):
            address = symbol_factory.BitVecVal(address, 256)

        from mythril_tpu.laser.strategy.beam import BeamSearch
        from mythril_tpu.laser.strategy.constraint_strategy import (
            DelayConstraintStrategy,
        )

        strategies = {
            "dfs": DepthFirstSearchStrategy,
            "bfs": BreadthFirstSearchStrategy,
            "naive-random": ReturnRandomNaivelyStrategy,
            "weighted-random": ReturnWeightedRandomStrategy,
            "beam-search": BeamSearch,
            "pending": DelayConstraintStrategy,
        }
        try:
            strategy_class = strategies[strategy]
        except KeyError:
            raise ValueError(f"invalid search strategy {strategy!r}")

        requires_statespace = compulsory_statespace or (
            run_analysis_modules
            and len(
                ModuleLoader().get_detection_modules(EntryPoint.POST, modules)
            )
            > 0
        )

        # static bytecode pre-analysis (mythril_tpu/preanalysis/): one CFG
        # + effect-summary pass per contract before LASER starts. The
        # summary feeds the engine/strategies as effect hints; the
        # reachable-opcode set (non-None ONLY when gating is sound:
        # runtime-mode code, no dynloader, resolved CFG, no CREATE) gates
        # detection-module attachment below.
        from mythril_tpu import preanalysis

        self.preanalysis = None
        gating = None
        if preanalysis.enabled():
            try:
                code_object = (
                    contract.creation_disassembly
                    if contract.creation_code is not None
                    and contract.is_create_mode
                    else contract.disassembly
                )
            except AttributeError:
                code_object = None
            self.preanalysis = preanalysis.get_code_summary(code_object)
            gating = preanalysis.gating_opcodes(contract, dynloader)

        # vmapped frontier (laser/frontier/): on for analysis runs unless
        # gated off, but never when a full per-instruction statespace was
        # compulsorily requested (--statespace-json / graph dumps expect
        # interior snapshots of straight-line runs, which batched steps
        # elide; the default analyze statespace only feeds POST modules,
        # which key on fork/call/return snapshots runs never contain)
        from mythril_tpu.laser import frontier

        self.laser = LaserEVM(
            dynamic_loader=dynloader,
            max_depth=max_depth,
            execution_timeout=execution_timeout,
            create_timeout=create_timeout,
            strategy=strategy_class,
            transaction_count=transaction_count,
            requires_statespace=requires_statespace,
            beam_width=(getattr(args, "beam_width", None)
                        if strategy == "beam-search" else None),
            preanalysis=self.preanalysis,
            vmap_frontier=frontier.enabled() and not compulsory_statespace,
        )
        self.laser.extend_strategy(BoundedLoopsStrategy, loop_bound=loop_bound)

        if not args.incremental_txs:
            from mythril_tpu.laser.tx_prioritiser import RfTxPrioritiser

            self.laser.tx_prioritiser = RfTxPrioritiser(
                contract, model_path=getattr(args, "rf_model_path", None)
            )

        # engine plugins (pruners/coverage/etc.) are registered here
        from mythril_tpu.laser.plugin.loader import LaserPluginLoader
        from mythril_tpu.laser.plugin.plugins import (
            CoveragePluginBuilder,
            DependencyPrunerBuilder,
            InstructionProfilerBuilder,
            MutationPrunerBuilder,
        )

        plugin_loader = LaserPluginLoader()
        plugin_loader.reset()
        plugin_loader.load(CoveragePluginBuilder())
        if not args.disable_mutation_pruner:
            plugin_loader.load(MutationPrunerBuilder())
        if not disable_dependency_pruning and not args.disable_dependency_pruning:
            plugin_loader.load(DependencyPrunerBuilder())
        if not args.disable_iprof:
            plugin_loader.load(InstructionProfilerBuilder())
        if args.enable_state_merging:
            from mythril_tpu.laser.plugin.plugins import (
                StateMergePluginBuilder,
            )

            plugin_loader.load(StateMergePluginBuilder())
        if args.enable_summaries:
            from mythril_tpu.laser.plugin.plugins.summary import (
                SymbolicSummaryPluginBuilder,
            )

            plugin_loader.load(SymbolicSummaryPluginBuilder())
        plugin_loader.instrument_virtual_machine(self.laser)

        if not args.disable_coverage_strategy:
            from mythril_tpu.laser.plugin.plugins.coverage import (
                CoverageStrategy,
            )

            coverage_plugin = plugin_loader.plugin_list.get("coverage")
            if coverage_plugin is not None:
                self.laser.extend_strategy(
                    CoverageStrategy, coverage_plugin=coverage_plugin
                )

        if run_analysis_modules:
            analysis_modules = ModuleLoader().get_detection_modules(
                EntryPoint.CALLBACK, white_list=modules,
                reachable_opcodes=gating,
            )
            self.laser.register_hooks(
                hook_type="pre",
                hook_dict=get_detection_module_hooks(
                    analysis_modules, hook_type="pre"
                ),
            )
            self.laser.register_hooks(
                hook_type="post",
                hook_dict=get_detection_module_hooks(
                    analysis_modules, hook_type="post"
                ),
            )

        # run symbolic execution
        if contract.creation_code is not None and contract.is_create_mode:
            self.laser.sym_exec(
                creation_code=contract.creation_code,
                contract_name=contract.name,
            )
        else:
            from mythril_tpu.laser.state.world_state import WorldState
            from mythril_tpu.disasm import Disassembly

            world_state = WorldState()
            account = world_state.create_account(
                balance=0,
                address=address.concrete_value,
                dynamic_loader=dynloader,
                concrete_storage=False,
                code=contract.disassembly,
            )
            account.contract_name = contract.name
            self.laser.sym_exec(
                world_state=world_state, target_address=address.concrete_value
            )

        # expose the statespace for POST modules and dumps
        self.nodes = self.laser.nodes
        self.edges = self.laser.edges
        self.tx_id_to_address = {}

    @property
    def calls(self) -> List[Call]:
        """Extract Call records from the statespace (reference :250-330)."""
        out = []
        for node in self.nodes.values():
            for index, state in enumerate(node.states):
                instruction = state.get_current_instruction()
                if instruction is None:
                    continue
                if instruction.opcode in (
                    "CALL", "CALLCODE", "DELEGATECALL", "STATICCALL"
                ):
                    call = get_call_from_state(state, node, index)
                    if call is not None:
                        out.append(call)
        return out

"""Linear-sweep EVM disassembler.

Behavioral parity with reference mythril/disassembler/asm.py (bytes ->
instruction records with address/opcode/argument, EASM text). The record is
a NamedTuple rather than a dict so the engine can index it cheaply.
"""

import re
from typing import List, NamedTuple, Optional

from mythril_tpu.support import opcodes


class Instr(NamedTuple):
    address: int          # byte offset in the code
    opcode: str           # mnemonic, e.g. "PUSH2"
    byte: int             # raw opcode byte
    # PUSH operand: bytes when fully concrete, tuple of int/BitVec(8) when
    # the code carries symbolic bytes (immutables patched at deploy time),
    # else None
    argument: Optional[object]

    @property
    def argument_int(self) -> Optional[int]:
        if self.argument is None:
            return None
        if isinstance(self.argument, bytes):
            return int.from_bytes(self.argument, "big")
        return None  # symbolic operand

    def to_easm(self) -> str:
        if isinstance(self.argument, bytes):
            return f"{self.address} {self.opcode} 0x{self.argument.hex()}"
        if self.argument is not None:
            return f"{self.address} {self.opcode} <symbolic>"
        return f"{self.address} {self.opcode}"


def strip_metadata(code):
    """Drop the CBOR metadata trailer solc appends (…a264…0033 / …a165…)."""
    if not isinstance(code, bytes):
        return code  # symbolic code: trailer scan needs concrete bytes
    if len(code) >= 2:
        trailer_len = int.from_bytes(code[-2:], "big")
        if 0 < trailer_len <= len(code) - 2:
            candidate = code[-(trailer_len + 2):-2]
            # CBOR map header 0xa1/0xa2 with 'ipfs'/'bzzr'/'solc' keys
            if candidate[:1] in (b"\xa1", b"\xa2") and (
                b"ipfs" in candidate or b"bzzr" in candidate or b"solc" in candidate
            ):
                return code[: -(trailer_len + 2)]
    return code


def disassemble(code) -> List[Instr]:
    """Linear sweep; PUSH operands are consumed (truncated operand is padded).

    `code` is bytes, or a sequence of int/BitVec(8) entries when deploy-time
    patching left symbolic bytes in the runtime code (solidity immutables —
    reference asm.py:109-141 threads tuples the same way). A symbolic byte
    in an *opcode* position disassembles as INVALID; symbolic bytes only
    ever appear in PUSH operands in practice."""
    out: List[Instr] = []
    pc = 0
    length = len(code)
    symbolic_code = not isinstance(code, (bytes, bytearray))
    while pc < length:
        byte = code[pc]
        if not isinstance(byte, int):
            out.append(Instr(pc, "INVALID", 0xFE, None))
            pc += 1
            continue
        name = opcodes.name_of(byte)
        width = opcodes.push_width(name)
        if width:
            operand = tuple(code[pc + 1 : pc + 1 + width])
            if len(operand) < width:
                operand = operand + (0,) * (width - len(operand))
            if not symbolic_code or all(isinstance(b, int) for b in operand):
                operand = bytes(operand)
            out.append(Instr(pc, name, byte, operand))
            pc += 1 + width
        else:
            out.append(Instr(pc, name, byte, None))
            pc += 1
    return out


def instrs_to_easm(instrs: List[Instr]) -> str:
    return "\n".join(i.to_easm() for i in instrs) + "\n"


_EASM_LINE = re.compile(
    r"^(?:(\d+)\s+)?([A-Z][A-Z0-9]*|UNKNOWN_0x[0-9a-fA-F]{2})"
    r"(?:\s+(0x[0-9a-fA-F]+|@[A-Za-z_][A-Za-z0-9_]*))?$"
)
_LABEL_LINE = re.compile(r"^:([A-Za-z_][A-Za-z0-9_]*)$")


def easm_to_code(easm: str) -> bytes:
    """Assemble EASM text to bytecode.

    Supports labels to avoid hand-counted jump offsets:
        :loop           defines a label at the next instruction
        PUSH2 @loop     references it (operand patched after layout)
    """
    blob = bytearray()
    labels = {}
    fixups = []  # (offset, width, label_name, source_line)
    for line in easm.splitlines():
        line = line.strip()
        if not line or line.startswith("#"):
            continue
        label_match = _LABEL_LINE.match(line)
        if label_match:
            name = label_match.group(1)
            if name in labels:
                raise ValueError(f"duplicate label :{name}")
            labels[name] = len(blob)
            continue
        match = _EASM_LINE.match(line)
        if not match:
            raise ValueError(f"cannot parse EASM line: {line!r}")
        _, mnemonic, arg = match.groups()
        if mnemonic.startswith("UNKNOWN_0x"):
            if arg is not None:
                raise ValueError(f"{mnemonic} takes no operand: {line!r}")
            blob.append(int(mnemonic[10:], 16))
            continue
        spec = opcodes.BY_NAME.get(mnemonic)
        if spec is None:
            raise ValueError(f"unknown mnemonic {mnemonic!r}")
        blob.append(spec.byte)
        width = opcodes.push_width(mnemonic)
        if width:
            if arg is None:
                raise ValueError(f"{mnemonic} needs an operand")
            if arg.startswith("@"):
                fixups.append((len(blob), width, arg[1:], line))
                blob += b"\x00" * width
            else:
                try:
                    blob += int(arg, 16).to_bytes(width, "big")
                except OverflowError:
                    raise ValueError(
                        f"operand {arg} does not fit {mnemonic}: {line!r}"
                    ) from None
        elif arg is not None:
            raise ValueError(f"{mnemonic} takes no operand: {line!r}")
    for offset, width, name, line in fixups:
        if name not in labels:
            raise ValueError(f"undefined label @{name}: {line!r}")
        try:
            blob[offset:offset + width] = labels[name].to_bytes(width, "big")
        except OverflowError:
            raise ValueError(
                f"label @{name}={labels[name]} does not fit: {line!r}"
            ) from None
    return bytes(blob)

"""Bytecode -> instruction stream, EASM rendering, selector discovery."""

from mythril_tpu.disasm.asm import Instr, disassemble, instrs_to_easm  # noqa: F401
from mythril_tpu.disasm.disassembly import Disassembly  # noqa: F401

"""Disassembly: the code object carried by every account in the engine.

Parity with reference mythril/disassembler/disassembly.py:10 — holds the
bytecode, the instruction list, a pc->instruction index, JUMPDEST set, and
the function-selector -> entry-address map discovered from the solc
dispatcher pattern (reference disassembly.py:42-113).
"""

from typing import Dict, List, Optional

from mythril_tpu.disasm.asm import Instr, disassemble, instrs_to_easm, strip_metadata
from mythril_tpu.utils.keccak import keccak256


def _normalize(code):
    if isinstance(code, bytes):
        return code
    if isinstance(code, bytearray):
        return bytes(code)
    if isinstance(code, str):
        text = code.strip()
        if text.startswith("0x"):
            text = text[2:]
        return bytes.fromhex(text) if text else b""
    if isinstance(code, (tuple, list)):
        # deploy-time-patched code with symbolic bytes (immutables); keep
        # symbolic entries, collapse to bytes when fully concrete
        if all(isinstance(b, int) for b in code):
            return bytes(code)
        return tuple(code)
    raise TypeError(f"unsupported code type {type(code)!r}")


def _concrete_projection(bytecode) -> bytes:
    """Concrete view for hashing/reporting: symbolic bytes read as 0x00."""
    if isinstance(bytecode, bytes):
        return bytecode
    return bytes(b if isinstance(b, int) else 0 for b in bytecode)


class Disassembly:
    def __init__(self, code, enable_online_lookup: bool = False):
        self.bytecode = _normalize(code)
        # the CBOR metadata trailer is data, not code: sweep only the stripped
        # region (reference asm.py:119-122 trims the swarm-hash trailer too)
        self.instruction_list: List[Instr] = disassemble(strip_metadata(self.bytecode))
        self._index_by_address: Dict[int, int] = {
            ins.address: i for i, ins in enumerate(self.instruction_list)
        }
        self.valid_jump_destinations = frozenset(
            ins.address for ins in self.instruction_list if ins.opcode == "JUMPDEST"
        )
        # selector (hex str, no 0x) -> dispatch target pc
        self.function_entries: Dict[str, int] = _find_function_entries(
            self.instruction_list
        )
        # reverse index: entry pc -> selector (function_name_for_pc fires
        # per CFG node during execution, and the preanalysis effect
        # summaries project per-selector cones through it — a linear scan
        # per call was O(functions) on the engine's node-creation path).
        # setdefault keeps the FIRST selector when two selectors share an
        # entry pc, matching the replaced scan's first-match behavior
        self.entry_to_selector: Dict[int, str] = {}
        for selector, pc in self.function_entries.items():
            self.entry_to_selector.setdefault(pc, selector)
        # parity with reference func_hashes/function_name_to_address fields
        self.func_hashes: List[str] = list(self.function_entries)
        self.bytecode_hash: bytes = keccak256(_concrete_projection(self.bytecode))
        # preanalysis.get_code_summary memoizes its CodeSummary here (the
        # code object is immutable); absence of the attribute = not yet
        # computed, None = computed-and-unavailable (symbolic/empty code)

    def __len__(self) -> int:
        return len(self.bytecode)

    def instruction_at(self, pc: int) -> Optional[Instr]:
        idx = self._index_by_address.get(pc)
        return self.instruction_list[idx] if idx is not None else None

    def index_of_address(self, pc: int) -> Optional[int]:
        return self._index_by_address.get(pc)

    def get_easm(self) -> str:
        return instrs_to_easm(self.instruction_list)

    def function_name_for_pc(self, pc: int) -> Optional[str]:
        selector = self.entry_to_selector.get(pc)
        return f"_function_0x{selector}" if selector is not None else None


def _find_function_entries(instrs: List[Instr]) -> Dict[str, int]:
    """Scan the dispatcher: PUSH4 <sel> ... EQ ... PUSH <target> JUMPI.

    Recognizes both the classic `DUP1 PUSH4 EQ PUSH JUMPI` ladder and the
    `PUSH4 DUP2 EQ`-style variants by looking at small windows around each
    PUSH4 (reference disassembly.py:42-53 uses the same pattern idea).
    """
    entries: Dict[str, int] = {}
    for i, ins in enumerate(instrs):
        # symbolic (tuple) operands can't name a selector
        if ins.opcode != "PUSH4" or not isinstance(ins.argument, bytes):
            continue
        window = instrs[i + 1 : i + 5]
        names = [w.opcode for w in window]
        if "EQ" not in names:
            continue
        # find the jump target: the next PUSH before a JUMPI in the window+2
        tail = instrs[i + 1 : i + 6]
        target = None
        for j, w in enumerate(tail):
            if w.opcode == "JUMPI":
                for back in reversed(tail[:j]):
                    if back.opcode.startswith("PUSH") and back.argument is not None:
                        target = back.argument_int
                        break
                break
        if target is not None:
            entries[ins.argument.hex()] = target
    return entries

"""Concrete replay pass: build the initial world state from the input json
and execute the recorded transactions with the TraceFinder plugin on
(reference mythril/concolic/find_trace.py:41-76)."""

import binascii
from copy import deepcopy
from typing import List, Tuple

from mythril_tpu.concolic.concrete_data import ConcreteData
from mythril_tpu.disasm.disassembly import Disassembly
from mythril_tpu.laser.plugin.plugins.trace import TraceFinder
from mythril_tpu.laser.state.account import Account
from mythril_tpu.laser.state.world_state import WorldState
from mythril_tpu.laser.svm import LaserEVM
from mythril_tpu.laser.transaction.concolic import execute_transaction
from mythril_tpu.laser.transaction.models import tx_id_manager
from mythril_tpu.smt import symbol_factory
from mythril_tpu.support.args import args
from mythril_tpu.support.time_handler import time_handler


def _to_int(value, default: int = 0) -> int:
    if value is None:
        return default
    if isinstance(value, int):
        return value
    return int(value, 16) if value.startswith("0x") else int(value)


def setup_concrete_initial_state(concrete_data: ConcreteData) -> WorldState:
    world_state = WorldState()
    for address, details in concrete_data["initialState"]["accounts"].items():
        code_hex = details.get("code", "0x")
        account = Account(
            int(address, 16),
            code=Disassembly(code_hex[2:] if code_hex.startswith("0x")
                             else code_hex),
            concrete_storage=True,
            nonce=details.get("nonce", 0),
        )
        world_state.put_account(account)
        storage = details.get("storage") or {}
        for key, value in storage.items():
            account.storage[symbol_factory.BitVecVal(_to_int(key), 256)] = \
                symbol_factory.BitVecVal(_to_int(value), 256)
        balance = _to_int(details.get("balance", 0))
        if balance:
            account.add_balance(symbol_factory.BitVecVal(balance, 256))
    return world_state


def concrete_execution(
    concrete_data: ConcreteData,
) -> Tuple[WorldState, List]:
    """Returns (initial world state, per-tx (pc, tx_id) trace)."""
    args.pruning_factor = 1
    tx_id_manager.restart_counter()
    init_state = setup_concrete_initial_state(concrete_data)
    laser_evm = LaserEVM(execution_timeout=1000)
    laser_evm.open_states = [deepcopy(init_state)]
    tracer = TraceFinder()
    tracer.initialize(laser_evm)
    time_handler.start_execution(laser_evm.execution_timeout)
    for transaction in concrete_data["steps"]:
        if transaction["address"] == "":
            # creation step (same shape runner.flip_branches handles)
            from mythril_tpu.laser.transaction.symbolic import (
                execute_contract_creation,
            )

            for world_state in laser_evm.open_states[:]:
                execute_contract_creation(
                    laser_evm, transaction["input"][2:],
                    world_state=world_state,
                )
            continue
        execute_transaction(
            laser_evm,
            callee_address=_to_int(transaction["address"]),
            caller_address=_to_int(transaction["origin"]),
            data=list(binascii.a2b_hex(transaction["input"][2:])),
            gas_price=_to_int(transaction.get("gasPrice"), 0x773594000),
            gas_limit=_to_int(transaction.get("gasLimit"), 8_000_000),
            value=_to_int(transaction.get("value", 0)),
        )
    tx_id_manager.restart_counter()
    return init_state, tracer.tx_trace

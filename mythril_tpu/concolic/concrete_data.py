"""Typed schema of the concolic input json
(reference mythril/concolic/concrete_data.py).

Shape:
{
  "initialState": {"accounts": {addr: {"code": "0x..", "nonce": int,
                                        "balance": "0x..",
                                        "storage": {slot: value}}}},
  "steps": [{"address": "0x..", "origin": "0x..", "input": "0x..",
             "value": "0x..", "gasLimit": "0x..", "gasPrice": "0x.."}]
}
"""

from typing import Dict, List, TypedDict


class AccountData(TypedDict):
    code: str
    nonce: int
    balance: str
    storage: dict


class InitialState(TypedDict):
    accounts: Dict[str, AccountData]


class TransactionData(TypedDict, total=False):
    address: str
    origin: str
    input: str
    value: str
    gasLimit: str
    gasPrice: str


class ConcreteData(TypedDict):
    initialState: InitialState
    steps: List[TransactionData]

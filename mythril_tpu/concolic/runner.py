"""Concolic driver: concrete replay -> symbolic flip pass
(reference mythril/concolic/concolic_execution.py:17-76; CLI entry
`myth concolic input.json --branches 34,57`)."""

from copy import deepcopy
from typing import Any, Dict, List

from mythril_tpu.concolic.concrete_data import ConcreteData
from mythril_tpu.concolic.find_trace import concrete_execution
from mythril_tpu.laser.state.world_state import WorldState
from mythril_tpu.laser.strategy.concolic import ConcolicStrategy
from mythril_tpu.laser.svm import LaserEVM
from mythril_tpu.laser.transaction.models import tx_id_manager
from mythril_tpu.laser.transaction.symbolic import (
    execute_contract_creation,
    execute_message_call,
)
from mythril_tpu.smt import symbol_factory
from mythril_tpu.support.args import args
from mythril_tpu.support.time_handler import time_handler


def flip_branches(
    init_state: WorldState,
    concrete_data: ConcreteData,
    jump_addresses: List[str],
    trace: List,
) -> List[Dict[str, Any]]:
    """Symbolically replay the tx steps along `trace`, flipping each JUMPI
    in `jump_addresses`; returns one concretized tx sequence per flip."""
    tx_id_manager.restart_counter()
    laser_evm = LaserEVM(
        execution_timeout=600,
        use_reachability_check=False,
        transaction_count=10,
    )
    laser_evm.open_states = [deepcopy(init_state)]
    laser_evm.strategy = ConcolicStrategy(
        work_list=laser_evm.work_list,
        max_depth=100,
        trace=trace,
        flip_branch_addresses=jump_addresses,
    )
    time_handler.start_execution(laser_evm.execution_timeout)
    for transaction in concrete_data["steps"]:
        address = transaction["address"]
        if address == "":
            for world_state in laser_evm.open_states[:]:
                execute_contract_creation(
                    laser_evm, transaction["input"][2:],
                    world_state=world_state,
                )
        else:
            execute_message_call(
                laser_evm,
                symbol_factory.BitVecVal(int(address, 16), 256),
            )
    return [laser_evm.strategy.results.get(addr)
            for addr in jump_addresses]


def concolic_execution(
    concrete_data: ConcreteData,
    jump_addresses: List,
    solver_timeout: int = 100000,
) -> List[Dict[str, Any]]:
    init_state, trace = concrete_execution(concrete_data)
    args.solver_timeout = solver_timeout
    return flip_branches(
        init_state=init_state,
        concrete_data=concrete_data,
        jump_addresses=[str(addr) for addr in jump_addresses],
        trace=trace,
    )


def run_concolic(concrete_data: ConcreteData, branches: List[int],
                 solver_timeout: int = 100000) -> List[Dict[str, Any]]:
    """CLI adapter (interfaces/cli.py `concolic` subcommand)."""
    return concolic_execution(concrete_data, branches, solver_timeout)

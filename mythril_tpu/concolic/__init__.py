"""Concolic mode: concrete replay -> trace -> branch flipping
(reference mythril/concolic/, 193 LoC)."""

from mythril_tpu.concolic.runner import concolic_execution, run_concolic  # noqa: F401

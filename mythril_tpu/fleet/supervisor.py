"""Fleet supervisor: spawns the shard workers, routes /analyze by
content digest, health-probes, crash-only restarts, and rolls every
shard's live metrics into one /metrics.

Front-door endpoints (loopback, like the single-process daemon):

  POST /analyze   routed to the digest's rendezvous shard and proxied;
                  a shard that dies or faults mid-request re-routes the
                  request ONCE to a surviving shard (fleet.shard
                  `worker_requeue`), then answers `incomplete` — zero
                  lost requests, never a hang.
  POST /evict     broadcast to every live shard (a tenant's warm memos
                  may live on any shard its digests routed to).
  GET  /healthz   fleet rollup: per-shard liveness, ports, restarts.
  GET  /fleetz    per-shard heat map (requests, warm hits, net-tier
                  hits) for the soak harness — read from each shard's
                  /snapshot.
  GET  /metrics   one Prometheus exposition for the whole fleet: each
                  shard's /snapshot merged (counters summed, ratio
                  gauges recomputed from the merged counters) with the
                  supervisor's own snapshot, plus per-shard heat-map
                  series labelled {shard="N"}.

Failure model (registered fault site fleet.shard, retry): the
supervisor never trusts a shard to stay up. A dead process or three
consecutive failed health probes triggers a crash-only restart —
fleet_shard_restarts, `retry` event — and the replacement re-warms from
the shared network tier, so the only cost of a shard death is the warm
MEMORY affinity of its digests until traffic re-warms it. SIGTERM
drains the fleet: stop admitting, SIGTERM every worker (each finishes
its in-flight requests under the PR-13 drain), then stop the front.
"""

import json
import logging
import os
import subprocess
import sys
import tempfile
import threading
import time
from http.client import HTTPConnection
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from typing import Dict, List, Optional

from mythril_tpu.fleet import probe_interval_s, start_timeout_s
from mythril_tpu.fleet.router import ShardRouter, request_digest
from mythril_tpu.serve.daemon import (
    DEFAULT_DEADLINE_S,
    DEFAULT_DRAIN_TIMEOUT_S,
)

log = logging.getLogger(__name__)

# consecutive failed health probes before a live-looking process is
# declared wedged and crash-only restarted
PROBE_FAILURE_LIMIT = 3


class _Shard:
    """One worker incarnation (the proc handle is Popen-like: tests
    inject stubs through the supervisor's spawn override)."""

    def __init__(self, shard_id: int):
        self.shard_id = shard_id
        self.proc = None
        self.port: Optional[int] = None
        self.restarts = 0
        self.probe_failures = 0

    @property
    def alive(self) -> bool:
        return self.proc is not None and self.proc.poll() is None \
            and self.port is not None


class FleetSupervisor:
    def __init__(self, shards: int, tx_count: int = 1,
                 modules: Optional[List[str]] = None,
                 http_port: Optional[int] = None,
                 spawn=None):
        self.shard_count = max(1, int(shards))
        self.tx_count = tx_count
        self.modules = modules
        self.http_port = http_port
        self.port: Optional[int] = None
        # spawn(shard_id, announce_path) -> Popen-like; the default
        # launches the real worker module. Tests inject stub shards.
        self._spawn = spawn or self._spawn_worker
        self.router = ShardRouter(range(self.shard_count))
        self._shards: Dict[int, _Shard] = {
            sid: _Shard(sid) for sid in range(self.shard_count)}
        self._lock = threading.Lock()
        self._draining = False
        self.drained = threading.Event()
        self._probe_stop = threading.Event()
        self._probe_thread: Optional[threading.Thread] = None
        self._http: Optional[ThreadingHTTPServer] = None
        self._http_thread: Optional[threading.Thread] = None
        self._run_dir: Optional[str] = None

    # -- lifecycle -----------------------------------------------------------

    def start(self) -> "FleetSupervisor":
        from mythril_tpu.resilience import faults
        from mythril_tpu.smt.solver.statistics import SolverStatistics
        from mythril_tpu.support.args import args

        SolverStatistics().enabled = True
        faults.configure_from_env(getattr(args, "inject_fault", None))
        self._run_dir = tempfile.mkdtemp(prefix="mythril-fleet-")
        for shard in self._shards.values():
            self._start_shard(shard)
        if self.http_port is not None:
            self._http = ThreadingHTTPServer(
                ("127.0.0.1", self.http_port), _FleetHandler)
            self._http.daemon_threads = True
            self._http.fleet = self
            self.port = self._http.server_address[1]
            self._http_thread = threading.Thread(
                target=self._http.serve_forever,
                name="mythril-fleet-http", daemon=True)
            self._http_thread.start()
        self._probe_thread = threading.Thread(
            target=self._probe_loop, name="mythril-fleet-probe",
            daemon=True)
        self._probe_thread.start()
        log.info("fleet supervisor up: %d shard(s), port=%s",
                 self.shard_count, self.port)
        return self

    def _spawn_worker(self, shard_id: int, announce_path: str):
        """Launch one real worker process. stdout/stderr go to a log
        file (a filled pipe nobody drains would wedge the worker)."""
        log_path = os.path.join(self._run_dir,
                                f"shard-{shard_id}.log")
        log_fd = open(log_path, "ab")
        argv = [sys.executable, "-m", "mythril_tpu.fleet.worker",
                "--shard-id", str(shard_id),
                "--announce", announce_path,
                "--tx-count", str(self.tx_count)]
        if self.modules:
            argv += ["--modules", ",".join(self.modules)]
        proc = subprocess.Popen(argv, stdout=log_fd, stderr=log_fd,
                                close_fds=True)
        log_fd.close()
        return proc

    def _start_shard(self, shard: _Shard) -> bool:
        """Spawn one incarnation and wait for its announce handshake.
        The announce path is per-incarnation so a crashed worker's
        stale announcement can never be mistaken for the new one."""
        announce = os.path.join(
            self._run_dir,
            f"shard-{shard.shard_id}.{shard.restarts}.json")
        try:
            shard.proc = self._spawn(shard.shard_id, announce)
        except Exception as error:
            log.error("spawning shard %d failed: %r",
                      shard.shard_id, error)
            shard.proc = None
            return False
        deadline = time.monotonic() + start_timeout_s()
        while time.monotonic() < deadline:
            if shard.proc.poll() is not None:
                log.error("shard %d exited rc=%s before announcing",
                          shard.shard_id, shard.proc.poll())
                return False
            try:
                with open(announce) as fd:
                    info = json.load(fd)
                shard.port = int(info["port"])
                shard.probe_failures = 0
                log.info("shard %d announced on port %d",
                         shard.shard_id, shard.port)
                return True
            except (OSError, ValueError, KeyError):
                time.sleep(0.05)
        log.error("shard %d did not announce within %.0fs",
                  shard.shard_id, start_timeout_s())
        return False

    # -- health probe / crash-only restart ----------------------------------

    def _probe_loop(self) -> None:
        interval = probe_interval_s()
        while not self._probe_stop.wait(interval):
            if self._draining:
                return
            for shard in list(self._shards.values()):
                if self._draining or self._probe_stop.is_set():
                    return
                self._probe_shard(shard)

    def _probe_shard(self, shard: _Shard) -> None:
        if shard.proc is None or shard.proc.poll() is not None:
            self._restart_shard(shard, "process dead")
            return
        try:
            code, _health = _http_call(
                shard.port, "GET", "/healthz",
                timeout=max(1.0, probe_interval_s()))
            if code in (200, 503):   # 503 = draining, still alive
                shard.probe_failures = 0
                return
            shard.probe_failures += 1
        except Exception:
            shard.probe_failures += 1
        if shard.probe_failures >= PROBE_FAILURE_LIMIT:
            self._restart_shard(
                shard, f"{shard.probe_failures} failed probes")

    def _restart_shard(self, shard: _Shard, reason: str) -> None:
        """Crash-only: kill whatever is left, spawn a replacement. The
        replacement re-warms from the shared network tier — nothing a
        dead shard settled is lost to the fleet."""
        from mythril_tpu.resilience import record_event
        from mythril_tpu.smt.solver.statistics import SolverStatistics

        log.warning("restarting shard %d (%s)", shard.shard_id, reason)
        if shard.proc is not None and shard.proc.poll() is None:
            try:
                shard.proc.kill()
                shard.proc.wait(timeout=10.0)
            except Exception:
                pass
        with self._lock:
            shard.port = None
            shard.probe_failures = 0
            shard.restarts += 1
        SolverStatistics().add_fleet_shard_restart()
        record_event("fleet.shard", "retry")
        self._start_shard(shard)

    # -- routing / proxy -----------------------------------------------------

    def _live_shard_ids(self, exclude=()) -> List[int]:
        with self._lock:
            return [shard.shard_id for shard in self._shards.values()
                    if shard.alive and shard.shard_id not in exclude]

    def handle_analyze(self, payload: dict):
        """Route one request to its digest's shard and proxy it; on a
        shard fault, re-route ONCE to a surviving shard, then answer
        `incomplete` (the fleet-level mirror of the daemon's
        requeue-once-then-incomplete worker discipline)."""
        from mythril_tpu.resilience import maybe_inject, record_event
        from mythril_tpu.smt.solver.statistics import SolverStatistics

        if self._draining:
            return 503, {"status": "rejected", "reason": "draining"}
        stats = SolverStatistics()
        digest = request_digest(payload.get("code", ""))
        timeout = float(payload.get("deadline_s")
                        or DEFAULT_DEADLINE_S) * 2 + 90.0
        tried: List[int] = []
        last_error = "no live shards"
        for attempt in range(2):
            shard_id = self.router.route(
                digest, live=self._live_shard_ids(exclude=tried))
            if shard_id is None:
                break
            with self._lock:
                port = self._shards[shard_id].port
            try:
                maybe_inject("fleet.shard")
                code, outcome = _http_call(
                    port, "POST", "/analyze", payload, timeout=timeout)
                if isinstance(outcome, dict):
                    outcome.setdefault("shard", shard_id)
                return code, outcome
            except Exception as error:
                last_error = repr(error)
                tried.append(shard_id)
                if attempt == 0:
                    record_event("fleet.shard", "worker_requeue")
                    stats.add_fleet_requeue()
                    log.warning(
                        "shard %d failed request mid-proxy (%s); "
                        "re-routing once to a surviving shard",
                        shard_id, last_error)
        record_event("fleet.shard", "degraded")
        return 504, {"status": "incomplete",
                     "reason": f"shard failure: {last_error}"}

    def handle_evict(self, tenant: str):
        """Broadcast eviction: a tenant's warm memos may live on every
        shard its digests routed to. Busy on any shard = busy."""
        results = {}
        for shard_id in self._live_shard_ids():
            with self._lock:
                port = self._shards[shard_id].port
            try:
                code, _body = _http_call(
                    port, "POST", "/evict", {"tenant": tenant},
                    timeout=90.0)
                results[shard_id] = code
            except Exception:
                results[shard_id] = None
        if results and all(code == 200 for code in results.values()):
            return 200, {"status": "ok", "evicted": tenant}
        return 409, {"status": "busy", "tenant": tenant,
                     "shards": {str(k): v for k, v in results.items()}}

    # -- observability -------------------------------------------------------

    def healthz(self) -> dict:
        with self._lock:
            shards = {
                str(shard.shard_id): {
                    "alive": shard.alive,
                    "port": shard.port,
                    "restarts": shard.restarts,
                }
                for shard in self._shards.values()}
        live = sum(1 for row in shards.values() if row["alive"])
        status = "draining" if self._draining else (
            "ok" if live == self.shard_count else "degraded")
        return {"status": status, "shards": shards,
                "live": live, "total": self.shard_count}

    def _shard_snapshots(self) -> Dict[int, Optional[dict]]:
        """Each live shard's /snapshot (None for dead/unreachable)."""
        snaps: Dict[int, Optional[dict]] = {}
        for shard_id in sorted(self._shards):
            with self._lock:
                shard = self._shards[shard_id]
                port = shard.port if shard.alive else None
            snap = None
            if port is not None:
                try:
                    _code, snap = _http_call(port, "GET", "/snapshot",
                                             timeout=10.0)
                except Exception:
                    snap = None
            snaps[shard_id] = snap if isinstance(snap, dict) else None
        return snaps

    def fleetz(self) -> dict:
        """The heat map the soak harness reads: per-shard request and
        warm-hit tallies from each shard's live snapshot."""
        health = self.healthz()
        snaps = self._shard_snapshots()
        heat = {}
        for shard_id, snap in snaps.items():
            row = dict(health["shards"][str(shard_id)])
            if snap is not None:
                counters = snap.get("counters", {})
                row.update({
                    "requests_admitted":
                        counters.get("serve_requests_admitted", 0),
                    "requests_completed":
                        counters.get("serve_requests_completed", 0),
                    "memo_hits": (counters.get("memory_hits", 0)
                                  + counters.get("quick_sat_hits", 0)),
                    "persistent_hits":
                        counters.get("persistent_hits", 0),
                    "net_tier_hits": counters.get("net_tier_hits", 0),
                    "net_tier_stores":
                        counters.get("net_tier_stores", 0),
                    "cdcl_settles": counters.get("cdcl_settles", 0),
                })
            heat[str(shard_id)] = row
        health["shards"] = heat
        return health

    def metrics_text(self) -> str:
        """One fleet-wide Prometheus exposition: every live shard's
        snapshot merged with the supervisor's own (counters summed,
        ratio gauges recomputed), plus per-shard heat-map series."""
        from mythril_tpu.observe import metrics

        snaps = self._shard_snapshots()
        merged = metrics.merge_snapshots(
            [metrics.snapshot()]
            + [snap for snap in snaps.values() if snap is not None])
        lines = [metrics.prometheus_text(
            merged, scrape_stamp=True).rstrip("\n")]
        for series, key in (
                ("fleet_shard_requests", "serve_requests_completed"),
                ("fleet_shard_warm_hits", None),
                ("fleet_shard_net_tier_hits", "net_tier_hits")):
            prom = f"mythril_tpu_{series}"
            lines.append(f"# TYPE {prom} counter")
            for shard_id, snap in snaps.items():
                if snap is None:
                    continue
                counters = snap.get("counters", {})
                if key is None:   # warm hits: every cache tier
                    value = (counters.get("memory_hits", 0)
                             + counters.get("quick_sat_hits", 0)
                             + counters.get("persistent_hits", 0))
                else:
                    value = counters.get(key, 0)
                lines.append(f'{prom}{{shard="{shard_id}"}} {value}')
        return "\n".join(lines) + "\n"

    # -- drain ---------------------------------------------------------------

    def drain(self, timeout: Optional[float] = None) -> bool:
        """Stop admitting, SIGTERM every worker (each drains its
        in-flight requests under the PR-13 discipline), then stop the
        front door. True = every shard exited within the budget."""
        budget = timeout if timeout is not None \
            else DEFAULT_DRAIN_TIMEOUT_S
        start = time.monotonic()
        self._draining = True
        self._probe_stop.set()
        if self._probe_thread is not None:
            self._probe_thread.join(timeout=probe_interval_s() + 5.0)
        clean = True
        for shard in self._shards.values():
            if shard.proc is not None and shard.proc.poll() is None:
                try:
                    shard.proc.terminate()
                except Exception:
                    pass
        for shard in self._shards.values():
            if shard.proc is None:
                continue
            remaining = max(0.5, budget - (time.monotonic() - start))
            try:
                shard.proc.wait(timeout=remaining)
            except Exception:
                clean = False
                try:
                    shard.proc.kill()
                    shard.proc.wait(timeout=10.0)
                except Exception:
                    pass
        if self._http is not None:
            try:
                self._http.shutdown()
                self._http.server_close()
            except Exception:
                pass
            if self._http_thread is not None:
                self._http_thread.join(timeout=5.0)
            self._http = None
        self.drained.set()
        log.info("fleet drained in %.2fs (clean=%s)",
                 time.monotonic() - start, clean)
        return clean


class _FleetHandler(BaseHTTPRequestHandler):
    protocol_version = "HTTP/1.1"

    @property
    def fleet(self) -> FleetSupervisor:
        return self.server.fleet

    def log_message(self, fmt, *args):
        log.debug("fleet http: " + fmt, *args)

    def _send_json(self, code: int, payload: dict) -> None:
        body = json.dumps(payload).encode()
        self.send_response(code)
        self.send_header("Content-Type", "application/json")
        self.send_header("Content-Length", str(len(body)))
        self.end_headers()
        self.wfile.write(body)

    def _send_text(self, code: int, text: str) -> None:
        body = text.encode()
        self.send_response(code)
        self.send_header("Content-Type",
                         "text/plain; version=0.0.4")
        self.send_header("Content-Length", str(len(body)))
        self.end_headers()
        self.wfile.write(body)

    def _read_body(self) -> Optional[dict]:
        try:
            length = int(self.headers.get("Content-Length", "0"))
            return json.loads(self.rfile.read(length) or b"{}")
        except Exception:
            return None

    def do_GET(self):
        if self.path == "/healthz":
            health = self.fleet.healthz()
            self._send_json(200 if health["status"] == "ok" else 503,
                            health)
            return
        if self.path == "/fleetz":
            self._send_json(200, self.fleet.fleetz())
            return
        if self.path == "/metrics":
            self._send_text(200, self.fleet.metrics_text())
            return
        self._send_json(404, {"error": f"unknown path {self.path}"})

    def do_POST(self):
        if self.path == "/analyze":
            payload = self._read_body()
            if not payload or "code" not in payload:
                self._send_json(400, {"error": "body must be JSON with "
                                               "at least a `code` key"})
                return
            code, outcome = self.fleet.handle_analyze(payload)
            self._send_json(code, outcome)
            return
        if self.path == "/evict":
            payload = self._read_body()
            if not payload or "tenant" not in payload:
                self._send_json(400, {"error": "body must be JSON with "
                                               "a `tenant` key"})
                return
            code, outcome = self.fleet.handle_evict(payload["tenant"])
            self._send_json(code, outcome)
            return
        self._send_json(404, {"error": f"unknown path {self.path}"})


def _http_call(port: int, method: str, path: str,
               payload: Optional[dict] = None,
               timeout: float = 30.0):
    """One loopback HTTP round trip to a shard; raises on transport
    failure (the caller's requeue discipline handles it)."""
    conn = HTTPConnection("127.0.0.1", port, timeout=timeout)
    try:
        body = json.dumps(payload).encode() if payload is not None \
            else None
        headers = {"Content-Type": "application/json"} \
            if body is not None else {}
        conn.request(method, path, body=body, headers=headers)
        response = conn.getresponse()
        raw = response.read()
        try:
            return response.status, json.loads(raw)
        except ValueError:
            return response.status, raw.decode(errors="replace")
    finally:
        conn.close()


def serve_forever_fleet(supervisor: FleetSupervisor) -> int:
    """CLI entry: start the fleet, announce, block until drained."""
    import signal

    supervisor.start()

    def _handler(_signum, _frame):
        threading.Thread(target=supervisor.drain, daemon=True).start()

    signal.signal(signal.SIGTERM, _handler)
    signal.signal(signal.SIGINT, _handler)
    print(f"mythril_tpu fleet listening on "
          f"http://127.0.0.1:{supervisor.port} "
          f"({supervisor.shard_count} shards; POST /analyze, "
          f"POST /evict, GET /healthz, GET /fleetz, GET /metrics); "
          f"SIGTERM drains", flush=True)
    supervisor.drained.wait()
    return 0

"""One fleet shard: a full engine process running the PR-13 serve
daemon, launched by the supervisor as

    python -m mythril_tpu.fleet.worker --shard-id N --announce PATH

The worker owns everything the single-process daemon owns — bounded
admission, per-tenant budgets, cross-request interleaved batches, warm
per-tenant contexts, the serve.* fault sites, SIGTERM drain — and adds
nothing: shard-ness lives entirely in the supervisor's routing and in
the shared network tier the worker mounts through
MYTHRIL_TPU_NET_TIER_DIR (inherited env). With the network tier
mounted, the worker forces disk-tier cache mode so every verdict it
settles is published where the whole fleet can serve it, and a
crash-only restart re-warms from what the previous incarnation (and
every sibling shard) already stored.

The announce file ({"pid", "port", "shard_id"}, atomic rename) is the
start handshake: the worker binds an ephemeral port (the supervisor
never guesses), writes the announcement, then blocks until drained.
SIGTERM drains: in-flight requests finish, the listener answers until
the last one resolves, then the process exits 0.
"""

import argparse
import logging
import os
import sys

log = logging.getLogger(__name__)


def main(argv=None) -> int:
    parser = argparse.ArgumentParser(prog="mythril_tpu.fleet.worker")
    parser.add_argument("--shard-id", type=int, required=True)
    parser.add_argument("--announce", required=True)
    parser.add_argument("--tx-count", type=int, default=1)
    parser.add_argument("--port", type=int, default=0)
    parser.add_argument("--modules", default=None)
    parsed = parser.parse_args(argv)

    logging.basicConfig(
        level=logging.INFO,
        format=f"%(asctime)s shard-{parsed.shard_id} %(levelname)s "
               "%(name)s: %(message)s")
    from mythril_tpu.fleet import net_tier_dir
    from mythril_tpu.serve.daemon import (
        ServeDaemon,
        install_signal_handlers,
    )
    from mythril_tpu.service.store import atomic_write_json
    from mythril_tpu.support.args import args as global_args
    from mythril_tpu.tune import apply_tuned_profile

    apply_tuned_profile()
    if net_tier_dir():
        # publish every settled verdict into the fleet-shared tier
        global_args.solve_cache = "disk"
    daemon = ServeDaemon(
        tx_count=parsed.tx_count,
        modules=parsed.modules.split(",") if parsed.modules else None,
        http_port=parsed.port)
    daemon.start()
    install_signal_handlers(daemon)
    if not atomic_write_json(parsed.announce, {
            "pid": os.getpid(),
            "port": daemon.port,
            "shard_id": parsed.shard_id}):
        log.error("could not write announce file %s", parsed.announce)
        daemon.drain(timeout=0.0)
        return 1
    log.info("shard %d serving on port %d (announce %s)",
             parsed.shard_id, daemon.port, parsed.announce)
    daemon.drained.wait()
    return 0


if __name__ == "__main__":
    sys.exit(main())

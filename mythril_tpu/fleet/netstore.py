"""The shared NETWORK result tier: the content-addressed disk tier
(service/store.py) promoted to an object-store-style directory every
shard in the fleet mounts (MYTHRIL_TPU_NET_TIER_DIR).

Nothing about the entry format changes — that is the point. The tier's
trust model was location-independent from the start:

  SAT    a hit is NEVER trusted as-is; the caller replays the stored
         assignment bits through Solver._reconstruct, which validates
         the rebuilt model against the ORIGINAL constraints. A
         fingerprint collision, a torn cross-host write, or a stale
         entry from another shard degrades to a safe miss, never a
         wrong verdict — which is exactly what makes the entries safe
         to serve from a directory ANY process can write.
  UNSAT  crosscheck provenance gates detection-path trust, same as the
         local tier.

What does change is the failure domain: a corrupt entry may now have
been written by a DIFFERENT shard. The subclass therefore carries its
own registered fault site (netstore.entry, quarantine): the READING
shard quarantines the entry and safe-misses — counted
net_tier_verify_rejects so the fleet /metrics rollup can see
cross-shard corruption separately from local-tier rot — while the
writing shard keeps running untouched. Writes reuse the PR-8
stale-lock discipline (support/lock.py) against the shared directory,
so a shard that dies mid-write can never wedge the tier for its
siblings: the lock's owner-pid liveness probe and max-age break apply
across the fleet.
"""

import logging
from typing import Optional

from mythril_tpu.fleet import net_tier_dir
from mythril_tpu.service.store import PersistentResultStore

log = logging.getLogger(__name__)


class NetworkResultStore(PersistentResultStore):
    """PersistentResultStore pointed at the fleet-shared directory,
    with the netstore.entry fault site on its read path."""

    is_network = True
    entry_site = "netstore.entry"

    def __init__(self, root: Optional[str] = None,
                 max_entries: Optional[int] = None,
                 max_bytes: Optional[int] = None):
        super().__init__(root=root or net_tier_dir() or None,
                         max_entries=max_entries, max_bytes=max_bytes)

    def _entry_guard(self, text: str) -> str:
        from mythril_tpu.resilience import corrupt_text, maybe_inject

        maybe_inject("netstore.entry")
        return corrupt_text("netstore.entry", text)

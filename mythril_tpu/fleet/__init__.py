"""Sharded serve fleet: multi-process workers behind one front door.

Every acceleration layer through PR 16 lives inside one process: the
GIL and the process-global term table cap a single daemon's throughput
no matter how fast the solver stack gets. This package breaks that
ceiling with three pieces:

  router      (router.py) a rendezvous-hash shard router keyed on the
              request's content digest (domain-separated with the
              FINGERPRINT SCHEMA version), so identical bytecode from
              DIFFERENT tenants lands on the same shard's warm memory
              tier — the cross-user shared-prefix observation behind
              ragged paged attention's serving story, applied to solve
              cones. Registered fault site fleet.route (disable):
              faults degrade to round-robin placement for the session.
  netstore    (netstore.py) the content-addressed disk tier promoted to
              a shared NETWORK tier: an object-store-style directory
              (MYTHRIL_TPU_NET_TIER_DIR) every shard mounts, with the
              PR-8 stale-lock discipline. Entries are safe to serve
              from anywhere because every SAT hit replay-verifies
              through Solver._reconstruct against the ORIGINAL
              constraints before being trusted; a corrupt shared entry
              quarantines on the READING shard as a safe miss
              (registered fault site netstore.entry).
  supervisor  (supervisor.py + worker.py) each shard worker is a full
              engine process running the PR-13 daemon (admission,
              per-tenant budgets, cross-request batching, SIGTERM
              drain); the supervisor health-probes, crash-only restarts
              dead shards (they re-warm from the shared tier), and
              re-routes a failed shard's in-flight requests once to a
              surviving shard (registered fault site fleet.shard).

Knobs (all env; see README "Serve fleet"):
  MYTHRIL_TPU_FLEET_SHARDS          worker count for `serve --shards`
                                    (CLI flag wins; 1 = single-process)
  MYTHRIL_TPU_NET_TIER_DIR          shared network-tier directory; unset
                                    = each process keeps a private disk
                                    tier under MYTHRIL_TPU_CACHE_DIR
  MYTHRIL_TPU_FLEET_PROBE_INTERVAL  supervisor health-probe cadence
                                    seconds (2.0)
  MYTHRIL_TPU_FLEET_START_TIMEOUT   per-shard start/announce wait
                                    seconds (120)
"""

import os

from mythril_tpu.support.env import env_float

FLEET_SHARDS_ENV = "MYTHRIL_TPU_FLEET_SHARDS"
NET_TIER_DIR_ENV = "MYTHRIL_TPU_NET_TIER_DIR"
PROBE_INTERVAL_ENV = "MYTHRIL_TPU_FLEET_PROBE_INTERVAL"
START_TIMEOUT_ENV = "MYTHRIL_TPU_FLEET_START_TIMEOUT"

DEFAULT_PROBE_INTERVAL_S = 2.0
DEFAULT_START_TIMEOUT_S = 120.0


def fleet_shards(cli_value=None) -> int:
    """Resolved shard count: CLI flag > env > 1 (single-process)."""
    if cli_value:
        return max(1, int(cli_value))
    return max(1, int(env_float(FLEET_SHARDS_ENV, 1)))


def net_tier_dir() -> str:
    """The shared network-tier root ('' = no network tier mounted)."""
    return os.environ.get(NET_TIER_DIR_ENV) or ""


def probe_interval_s() -> float:
    return max(0.05, env_float(PROBE_INTERVAL_ENV,
                               DEFAULT_PROBE_INTERVAL_S))


def start_timeout_s() -> float:
    return max(1.0, env_float(START_TIMEOUT_ENV, DEFAULT_START_TIMEOUT_S))

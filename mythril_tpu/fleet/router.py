"""Digest-keyed shard router: rendezvous (highest-random-weight) hashing
over the live shard set.

The key is the request's bytecode content digest, domain-separated with
the FINGERPRINT SCHEMA version — the same schema that keys the
content-addressed result tiers — so identical bytecode from DIFFERENT
tenants deterministically lands on the same shard and hits that shard's
warm memory tier (memory tier, quick-sat deque, prefix snapshots),
while a schema bump naturally re-shards alongside the tier wipe.

Rendezvous hashing instead of modulo: when a shard dies, only the keys
that scored it highest move (to their second-choice shard) — every
other key keeps its warm shard. Modulo would reshuffle almost the whole
key space on any membership change, cold-starting the entire fleet.

Registered fault site fleet.route (disable): any fault in the scoring —
injected or real — degrades to round-robin placement for the session
(fuse after repeated faults). Requests still land on a live shard;
only warm-tier affinity is lost. Every decision counts
fleet_shard_routes.
"""

import hashlib
from typing import List, Optional, Sequence

from mythril_tpu.service.fingerprint import FINGERPRINT_SCHEMA


def request_digest(code: str) -> str:
    """Content digest of a request's bytecode — the routing key (the
    same sha256 the daemon folds into its tenant-qualified origins)."""
    return hashlib.sha256(code.encode()).hexdigest()


def _score(digest: str, shard_id: int) -> int:
    raw = hashlib.sha256(
        b"mythril-tpu-fleet-route-v%d:%s:%d"
        % (FINGERPRINT_SCHEMA, digest.encode(), shard_id)).digest()
    return int.from_bytes(raw[:8], "big")


class ShardRouter:
    def __init__(self, shard_ids: Sequence[int]):
        self.shard_ids: List[int] = list(shard_ids)
        self._rr = 0

    def route(self, digest: str,
              live: Optional[Sequence[int]] = None) -> Optional[int]:
        """Pick the shard for `digest` among `live` (default: all
        registered shards). None only when no shard is live at all."""
        from mythril_tpu import resilience
        from mythril_tpu.resilience import maybe_inject
        from mythril_tpu.smt.solver.statistics import SolverStatistics

        candidates = list(live) if live is not None else self.shard_ids
        if not candidates:
            return None
        shard = None
        if not resilience.fuse_blown("fleet.route"):
            try:
                maybe_inject("fleet.route")
                shard = max(candidates,
                            key=lambda sid: _score(digest, sid))
            except Exception:
                resilience.note_stage_failure("fleet.route")
                shard = None
        if shard is None:
            # round-robin degradation: still a live shard, no affinity
            shard = candidates[self._rr % len(candidates)]
            self._rr += 1
        SolverStatistics().add_fleet_route()
        return shard

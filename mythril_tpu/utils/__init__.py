"""Cross-cutting utilities: keccak-256, u256 helpers, global flags, clocks."""

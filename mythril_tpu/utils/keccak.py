"""Self-contained Keccak-256 (the Ethereum hash; original pad, not SHA3-06).

The environment ships no native keccak (no eth-hash/pysha3/pycryptodome),
so the sponge is implemented here from the Keccak spec. It is used to
concretize symbolic hash placeholders (reference:
mythril/laser/ethereum/function_managers/keccak_function_manager.py:56-69)
and by the SHA3 opcode on concrete inputs. A C++ fast path can be layered
behind the same function later; correctness vectors live in
tests/test_keccak.py.
"""

from functools import lru_cache

_MASK64 = (1 << 64) - 1

_ROUND_CONSTANTS = (
    0x0000000000000001, 0x0000000000008082, 0x800000000000808A,
    0x8000000080008000, 0x000000000000808B, 0x0000000080000001,
    0x8000000080008081, 0x8000000000008009, 0x000000000000008A,
    0x0000000000000088, 0x0000000080008009, 0x000000008000000A,
    0x000000008000808B, 0x800000000000008B, 0x8000000000008089,
    0x8000000000008003, 0x8000000000008002, 0x8000000000000080,
    0x000000000000800A, 0x800000008000000A, 0x8000000080008081,
    0x8000000000008080, 0x0000000080000001, 0x8000000080008008,
)

# Rotation offsets r[x][y] for lane (x, y).
_ROTATIONS = (
    (0, 36, 3, 41, 18),
    (1, 44, 10, 45, 2),
    (62, 6, 43, 15, 61),
    (28, 55, 25, 21, 56),
    (27, 20, 39, 8, 14),
)

_RATE_BYTES = 136  # 1600-bit state, 512-bit capacity -> 136-byte rate


def _rotl64(value: int, shift: int) -> int:
    return ((value << shift) | (value >> (64 - shift))) & _MASK64


def _keccak_f1600(lanes):
    """One permutation over the 5x5 lane matrix (lanes[x][y], 64-bit ints)."""
    for rc in _ROUND_CONSTANTS:
        # theta
        col = [lanes[x][0] ^ lanes[x][1] ^ lanes[x][2] ^ lanes[x][3] ^ lanes[x][4]
               for x in range(5)]
        delta = [col[(x - 1) % 5] ^ _rotl64(col[(x + 1) % 5], 1) for x in range(5)]
        for x in range(5):
            d = delta[x]
            lanes[x] = [lane ^ d for lane in lanes[x]]
        # rho + pi
        moved = [[0] * 5 for _ in range(5)]
        for x in range(5):
            for y in range(5):
                moved[y][(2 * x + 3 * y) % 5] = _rotl64(lanes[x][y], _ROTATIONS[x][y])
        # chi
        for y in range(5):
            row = [moved[x][y] for x in range(5)]
            for x in range(5):
                lanes[x][y] = row[x] ^ ((~row[(x + 1) % 5]) & row[(x + 2) % 5])
        # iota
        lanes[0][0] ^= rc
    return lanes


def keccak256(data: bytes) -> bytes:
    """Keccak-256 digest of `data` (32 bytes)."""
    lanes = [[0] * 5 for _ in range(5)]
    # pad10*1 with the original Keccak domain byte 0x01
    padded = bytearray(data)
    pad_len = _RATE_BYTES - (len(padded) % _RATE_BYTES)
    padded += b"\x01" + b"\x00" * (pad_len - 2) + b"\x80" if pad_len >= 2 else b"\x81"
    # absorb
    for block_start in range(0, len(padded), _RATE_BYTES):
        block = padded[block_start:block_start + _RATE_BYTES]
        for i in range(_RATE_BYTES // 8):
            lane = int.from_bytes(block[8 * i:8 * i + 8], "little")
            x, y = i % 5, i // 5
            lanes[x][y] ^= lane
        _keccak_f1600(lanes)
    # squeeze (32 bytes < rate, single block)
    out = bytearray()
    for i in range(4):
        x, y = i % 5, i // 5
        out += lanes[x][y].to_bytes(8, "little")
    return bytes(out)


def keccak256_int(value: int, width_bytes: int = 32) -> int:
    """Hash a big-endian fixed-width integer; returns the digest as an int."""
    return int.from_bytes(keccak256(value.to_bytes(width_bytes, "big")), "big")


@lru_cache(maxsize=65536)
def function_selector(signature: str) -> bytes:
    """First four digest bytes of an ABI signature, e.g. 'transfer(address,uint256)'."""
    return keccak256(signature.encode())[:4]

from mythril_tpu.interfaces.cli import main

main()

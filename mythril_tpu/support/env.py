"""Shared environment-variable parsing (one implementation instead of a
try/except copy per module — the copies were already drifting)."""

import os


def env_float(name: str, default: float) -> float:
    """`float(os.environ[name])`, or `default` when unset/malformed — a
    mistyped knob must never crash a run at import time."""
    try:
        return float(os.environ[name])
    except (KeyError, ValueError):
        return default

"""Shared environment-variable parsing and knob resolution.

One implementation instead of a try/except copy per module (the copies
were already drifting) — and, since the autotune loop closed, the single
seam every tunable knob resolves through. Resolution precedence for a
knob read:

    explicit env var  >  CLI-provided value  >  tuned profile  >  default

The tuned tier is populated once per process by
mythril_tpu.tune.apply_tuned_profile() from the persisted per-platform
profile (service/calibration.py `tuned` section); because every consumer
already reads its knobs through env_float/env_int here, applying a
profile needs no per-site changes. An explicit env var is ALWAYS
absolute — a tuned profile can never override an operator's hand-set
value. resolve_source() exposes which tier actually supplied each knob,
so the stats JSON / heartbeat can stamp the fully-resolved configuration
(value + source) onto every run.
"""

import os
from typing import Dict, Optional, Tuple

# tuned-profile tier (mythril_tpu/tune/): knob env name -> value, set by
# apply_tuned_profile(); empty until a profile is applied
_TUNED: Dict[str, object] = {}
# CLI tier: a flag that maps 1:1 onto a knob records its value here (no
# current knob has a dedicated flag, but the tier keeps the documented
# precedence honest when one grows)
_CLI: Dict[str, object] = {}


def set_tuned(mapping: Dict[str, object]) -> None:
    """Install the tuned-profile tier (replaces any previous mapping)."""
    _TUNED.clear()
    _TUNED.update(mapping)


def tuned_values() -> Dict[str, object]:
    return dict(_TUNED)


def set_cli(name: str, value) -> None:
    """Record a CLI-flag-provided knob value (beats tuned, loses to env)."""
    _CLI[name] = value


def clear_overrides() -> None:
    """Drop the tuned and CLI tiers (tests / args.reset)."""
    _TUNED.clear()
    _CLI.clear()


def _resolve(name: str, default, cast) -> Tuple[object, str]:
    """(value, source) through the full precedence chain. A mistyped
    knob must never crash a run at import time: a PRESENT-but-malformed
    env var pins the built-in default (the pre-tuned-tier behavior —
    an explicit env var, even a broken one, is absolute and must never
    be silently replaced by a tuned value), while a malformed cli/tuned
    entry falls through to the next tier."""
    raw = os.environ.get(name)
    if raw is not None:
        try:
            return cast(raw), "env"
        except (TypeError, ValueError):
            return default, "default"
    for tier, source in ((_CLI, "cli"), (_TUNED, "tuned")):
        if name in tier:
            try:
                return cast(tier[name]), source
            except (TypeError, ValueError):
                pass
    return default, "default"


def resolve_source(name: str, default=None, kind: str = "float"
                   ) -> Tuple[object, str]:
    """(resolved value, source tier) for stamping — same chain the
    readers below use, without caching anything."""
    cast = {"int": _cast_int, "str": str}.get(kind, float)
    return _resolve(name, default, cast)


def _cast_int(value) -> int:
    return int(float(value))


def env_float(name: str, default: float) -> float:
    """Resolved float knob: env > cli > tuned > `default`."""
    return _resolve(name, default, float)[0]


def env_int(name: str, default: int) -> int:
    """Resolved int knob: env > cli > tuned > `default` (lenient cast:
    a tuned profile may round-trip ints through JSON floats)."""
    return _resolve(name, default, _cast_int)[0]


def env_str(name: str, default: Optional[str]) -> Optional[str]:
    """Resolved string knob: env > cli > tuned > `default` (categorical
    knobs — e.g. MYTHRIL_TPU_KERNEL's backend name)."""
    return _resolve(name, default, str)[0]

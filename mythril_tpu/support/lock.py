"""Inter-process file lock guarding the shared config/signature store
(reference mythril/support/lock.py:78).

POSIX-only flock with a stale-lock timeout; used around `~/.mythril`
bootstrap so concurrent CLI invocations don't race config.ini creation."""

import contextlib
import os
import time


class LockFile:
    def __init__(self, path: str, timeout_seconds: float = 10.0):
        self.path = path
        self.timeout_seconds = timeout_seconds
        self._handle = None

    def acquire(self) -> None:
        import fcntl

        deadline = time.monotonic() + self.timeout_seconds
        self._handle = open(self.path, "a+")
        while True:
            try:
                fcntl.flock(self._handle, fcntl.LOCK_EX | fcntl.LOCK_NB)
                return
            except OSError:
                if time.monotonic() > deadline:
                    # stale lock: proceed rather than deadlock the CLI
                    return
                time.sleep(0.05)

    def release(self) -> None:
        if self._handle is None:
            return
        import fcntl

        with contextlib.suppress(OSError):
            fcntl.flock(self._handle, fcntl.LOCK_UN)
        self._handle.close()
        self._handle = None

    def __enter__(self) -> "LockFile":
        self.acquire()
        return self

    def __exit__(self, *exc) -> None:
        self.release()

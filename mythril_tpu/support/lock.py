"""Inter-process file lock guarding the shared config/signature store
(reference mythril/support/lock.py:78).

POSIX-only flock; used around `~/.mythril` bootstrap and every
store/calibration write so concurrent CLI invocations don't race.

Stale-lock containment (resilience fault site `store.lock`): the lock
file records its owner (`pid ts`) on acquire. A contended acquire checks
whether the recorded owner is still alive (pid liveness probe) and
whether the lock has exceeded its max age — store/calibration critical
sections hold the lock for milliseconds, so a minutes-old lock is a
crashed or wedged holder, not a slow one. A stale lock is BROKEN once
(the path is unlinked and re-taken on a fresh inode; counted as a
`stale_break` resilience event) instead of deadlocking every later
store/calibration access. If the lock still cannot be acquired by the
timeout, acquire degrades to proceeding unlocked (counted `degraded`) —
every write under these locks is an atomic rename, so an unlocked writer
can lose a race, never corrupt the target."""

import contextlib
import logging
import os
import time

log = logging.getLogger(__name__)

MAX_AGE_ENV = "MYTHRIL_TPU_LOCK_MAX_AGE"
DEFAULT_MAX_AGE_S = 300.0


def _default_max_age() -> float:
    from mythril_tpu.support.env import env_float

    return env_float(MAX_AGE_ENV, DEFAULT_MAX_AGE_S)


def _pid_alive(pid: int) -> bool:
    try:
        os.kill(pid, 0)
    except ProcessLookupError:
        return False
    except OSError:
        # EPERM etc.: the pid exists but belongs to someone else
        return True
    return True


class LockFile:
    def __init__(self, path: str, timeout_seconds: float = 10.0,
                 stale_age_seconds: float = 0.0):
        self.path = path
        self.timeout_seconds = timeout_seconds
        self.stale_age_seconds = stale_age_seconds or _default_max_age()
        self._handle = None

    def acquire(self) -> None:
        import fcntl

        from mythril_tpu import resilience

        try:
            resilience.maybe_inject("store.lock")
        except resilience.InjectedFault:
            # injected lock-layer failure: degrade to unlocked (atomic
            # renames keep every guarded write safe, races just lose)
            resilience.record_event("store.lock", "degraded")
            self._handle = None
            return
        deadline = time.monotonic() + self.timeout_seconds
        self._handle = open(self.path, "a+")
        broke_stale = False
        while True:
            try:
                fcntl.flock(self._handle, fcntl.LOCK_EX | fcntl.LOCK_NB)
            except OSError:
                if not broke_stale and self._is_stale():
                    # break at most once per acquire: a second contention
                    # after the break is a LIVE holder on the new inode
                    broke_stale = True
                    self._break_stale()
                    continue
                if time.monotonic() > deadline:
                    # could not get the lock and it is not provably
                    # stale: proceed unlocked rather than deadlock the
                    # analysis on a cache lock
                    resilience.record_event("store.lock", "degraded")
                    log.warning(
                        "could not acquire %s within %.1fs (live holder?);"
                        " proceeding unlocked", self.path,
                        self.timeout_seconds)
                    return
                time.sleep(0.05)
            else:
                if not self._holds_current_inode():
                    # a contender broke the (stale) lock between our
                    # open and our flock: we hold the ORPHANED inode, so
                    # the flock means nothing — re-contend on the path's
                    # current inode instead of entering the critical
                    # section alongside the breaker
                    with contextlib.suppress(OSError):
                        self._handle.close()
                    self._handle = open(self.path, "a+")
                    continue
                self._write_owner()
                return

    def _holds_current_inode(self) -> bool:
        """A successful flock only excludes contenders of the SAME inode;
        after a stale-lock break the path may point to a fresh one."""
        try:
            return (os.fstat(self._handle.fileno()).st_ino
                    == os.stat(self.path).st_ino)
        except OSError:
            return False

    # -- stale detection ----------------------------------------------------

    def _read_owner(self):
        """(pid, stamp_mtime) recorded by the current holder, or None when
        the lock file carries no readable owner record."""
        try:
            with open(self.path) as fd:
                first = fd.readline().split()
            return int(first[0]) if first else None
        except (OSError, ValueError, IndexError):
            return None

    def _is_stale(self) -> bool:
        """A contended lock is stale when its recorded owner pid is dead,
        or when it is older than the max age (critical sections under
        these locks run for milliseconds)."""
        owner = self._read_owner()
        if owner is not None and owner != os.getpid() \
                and not _pid_alive(owner):
            log.warning("lock %s owner pid %d is dead", self.path, owner)
            return True
        try:
            age = time.time() - os.path.getmtime(self.path)
        except OSError:
            return False
        if age > self.stale_age_seconds:
            log.warning("lock %s is %.0fs old (max age %.0fs)",
                        self.path, age, self.stale_age_seconds)
            return True
        return False

    def _break_stale(self) -> None:
        """Unlink the stale lock path and re-open a fresh inode: the dead
        (or wedged) holder keeps its flock on the ORPHANED inode, and
        every future LockFile contends on the new one."""
        from mythril_tpu import resilience

        resilience.record_event("store.lock", "stale_break")
        log.warning("breaking stale lock %s", self.path)
        with contextlib.suppress(OSError):
            os.unlink(self.path)
        with contextlib.suppress(OSError):
            self._handle.close()
        self._handle = open(self.path, "a+")

    def _write_owner(self) -> None:
        """Record this process as the holder (pid liveness is what a
        contending process probes to detect a crashed holder)."""
        try:
            self._handle.seek(0)
            self._handle.truncate()
            self._handle.write(f"{os.getpid()} {int(time.time())}\n")
            self._handle.flush()
        except (OSError, ValueError):
            pass

    def release(self) -> None:
        if self._handle is None:
            return
        import fcntl

        with contextlib.suppress(OSError):
            fcntl.flock(self._handle, fcntl.LOCK_UN)
        with contextlib.suppress(OSError):
            self._handle.close()
        self._handle = None

    def __enter__(self) -> "LockFile":
        self.acquire()
        return self

    def __exit__(self, *exc) -> None:
        self.release()

"""Support layer: opcode metadata, global flag singleton, time budget, caches."""

"""4-byte function-selector database (reference mythril/support/signatures.py:225).

sqlite-backed store at ~/.mythril_tpu/signatures.db; selectors learned from
analyzed sources are added, lookups resolve `_function_0x...` names in
reports. The online 4byte.directory lookup is gated off (no egress)."""

import os
import sqlite3
import threading
from typing import List, Optional

from mythril_tpu.utils.keccak import function_selector

_lock = threading.Lock()

# common selectors so reports are readable out of the box
_BUILTIN_SIGNATURES = [
    "transfer(address,uint256)",
    "transferFrom(address,address,uint256)",
    "approve(address,uint256)",
    "balanceOf(address)",
    "totalSupply()",
    "allowance(address,address)",
    "owner()",
    "kill()",
    "withdraw()",
    "withdraw(uint256)",
    "deposit()",
    "mint(address,uint256)",
    "burn(uint256)",
    "fallback()",
    "setOwner(address)",
    "claimOwnership()",
    "transferOwnership(address)",
    "initialize()",
    "pause()",
    "unpause()",
]


class SignatureDB:
    _instance = None

    def __new__(cls, enable_online_lookup: bool = False, path: Optional[str] = None):
        if cls._instance is None:
            cls._instance = super().__new__(cls)
            cls._instance._init(path)
        return cls._instance

    def _init(self, path: Optional[str]):
        base = os.environ.get(
            "MYTHRIL_DIR", os.path.join(os.path.expanduser("~"), ".mythril_tpu")
        )
        os.makedirs(base, exist_ok=True)
        self.path = path or os.path.join(base, "signatures.db")
        with _lock, sqlite3.connect(self.path) as conn:
            conn.execute(
                "CREATE TABLE IF NOT EXISTS signatures "
                "(byte_sig VARCHAR(10), text_sig VARCHAR(255),"
                " PRIMARY KEY (byte_sig, text_sig))"
            )
        self.add_signatures(_BUILTIN_SIGNATURES)

    def add(self, byte_sig: str, text_sig: str) -> None:
        with _lock, sqlite3.connect(self.path) as conn:
            conn.execute(
                "INSERT OR IGNORE INTO signatures VALUES (?, ?)",
                (byte_sig.lower(), text_sig),
            )

    def add_signatures(self, text_signatures: List[str]) -> None:
        with _lock, sqlite3.connect(self.path) as conn:
            for text_sig in text_signatures:
                byte_sig = "0x" + function_selector(text_sig).hex()
                conn.execute(
                    "INSERT OR IGNORE INTO signatures VALUES (?, ?)",
                    (byte_sig, text_sig),
                )

    def get(self, byte_sig: str) -> List[str]:
        if not byte_sig.startswith("0x"):
            byte_sig = "0x" + byte_sig
        with _lock, sqlite3.connect(self.path) as conn:
            rows = conn.execute(
                "SELECT text_sig FROM signatures WHERE byte_sig = ?",
                (byte_sig.lower(),),
            ).fetchall()
        return [row[0] for row in rows]

    def import_solidity_file(self, file_path: str) -> None:
        """Best-effort scrape of `function name(args)` declarations."""
        import re

        pattern = re.compile(r"function\s+([A-Za-z0-9_]+)\s*\(([^)]*)\)")
        try:
            with open(file_path) as handle:
                source = handle.read()
        except OSError:
            return
        for name, params in pattern.findall(source):
            types = []
            for param in params.split(","):
                param = param.strip()
                if not param:
                    continue
                types.append(_canonical_type(param.split()[0]))
            self.add_signatures([f"{name}({','.join(types)})"])


def _canonical_type(type_name: str) -> str:
    aliases = {"uint": "uint256", "int": "int256", "byte": "bytes1"}
    return aliases.get(type_name, type_name)

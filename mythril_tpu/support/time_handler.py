"""Singleton wall-clock budget shared by engine and solver
(reference mythril/laser/ethereum/time_handler.py:19)."""

import time


class _TimeHandler:
    def __init__(self):
        self._start = None
        self._timeout = None

    def start_execution(self, execution_timeout_seconds) -> None:
        self._start = time.monotonic()
        self._timeout = execution_timeout_seconds or 0

    def time_remaining(self) -> float:
        """Seconds left in the budget; large if no budget started."""
        if self._start is None or not self._timeout:
            return 1e9
        return self._timeout - (time.monotonic() - self._start)


time_handler = _TimeHandler()

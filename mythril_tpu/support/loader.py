"""DynLoader — cached on-chain reads for dynamic analysis
(reference mythril/support/loader.py:104: read_storage :30, read_balance
:50, dynld code fetch :66; consumed by Storage lazy load, account.py, and
the EXTCODE* handlers)."""

import functools
import logging
from typing import Optional

from mythril_tpu.disasm.disassembly import Disassembly

log = logging.getLogger(__name__)


class DynLoader:
    def __init__(self, eth, active: bool = True):
        """eth: an EthJsonRpc-compatible client; active: fetch code of
        unknown callee contracts during execution (--no-onchain-data off).
        """
        self.eth = eth
        self.active = active

    @functools.lru_cache(2 ** 12)
    def read_storage(self, contract_address: str, index: int) -> str:
        if self.eth is None:
            raise ValueError("no RPC client configured")
        return self.eth.eth_getStorageAt(contract_address, index)

    @functools.lru_cache(2 ** 12)
    def read_balance(self, address: str) -> int:
        if self.eth is None:
            raise ValueError("no RPC client configured")
        return self.eth.eth_getBalance(address)

    @functools.lru_cache(2 ** 12)
    def dynld(self, dependency_address: str) -> Optional[Disassembly]:
        """Fetch and disassemble callee code for inter-contract analysis."""
        if not self.active or self.eth is None:
            return None
        log.debug("dynld %s", dependency_address)
        code = self.eth.eth_getCode(dependency_address)
        if code in (None, "", "0x"):
            return None
        return Disassembly(code[2:] if code.startswith("0x") else code)

"""Process-global analysis flags (reference mythril/support/support_args.py:31).

Populated by the analyzer frontend from CLI flags; read by the engine,
plugins, detection modules, and solver glue."""


class _Args:
    def __init__(self):
        self.solver_timeout = 25000            # ms per query
        self.execution_timeout = 86400         # s per contract
        self.create_timeout = 10               # s for creation tx
        self.max_depth = 128
        self.loop_bound = 3
        self.transaction_count = 2
        self.pruning_factor = None             # None -> auto
        self.unconstrained_storage = False
        self.parallel_solving = False
        self.call_depth_limit = 3
        self.iteration_count = 0
        self.solver_log = None
        self.sparse_pruning = False
        self.incremental_txs = True
        self.use_issue_annotations = False
        self.use_integer_module = True
        self.disable_dependency_pruning = False
        self.disable_mutation_pruner = False
        self.disable_coverage_strategy = False
        self.disable_iprof = False
        self.enable_state_merging = False
        self.enable_summaries = False
        self.solver_backend = "cpu"            # cpu | tpu (shadowed by cpu)
        self.solve_cache = "memory"            # off | memory | disk
        self.no_preanalysis = False            # --no-preanalysis escape hatch
        #   (MYTHRIL_TPU_PREANALYSIS=0/1 overrides; preanalysis.enabled())
        self.no_aig_opt = False                # --no-aig-opt escape hatch
        #   (MYTHRIL_TPU_AIG_OPT=0/1 overrides; preanalysis.aig_opt.enabled())
        self.no_incremental_prep = False       # --no-incremental-prep
        #   (MYTHRIL_TPU_INCR_PREP=0/1 overrides; smt.solver.incremental)
        self.no_vmap_frontier = False          # --no-vmap-frontier
        #   (MYTHRIL_TPU_VMAP_FRONTIER=0/1 overrides; laser.frontier)
        self.no_ragged = False                 # --no-ragged
        #   (MYTHRIL_TPU_RAGGED=0/1 overrides; tpu.router.ragged_enabled)
        self.no_frontier_fork = False          # --no-frontier-fork
        #   (MYTHRIL_TPU_FRONTIER_FORK=0/1 overrides; laser.frontier
        #   fork_enabled — device-side branching at symbolic JUMPI)
        self.beam_width = 8                    # --beam-search WIDTH
        self.transaction_sequences = None      # e.g. "[[0xa9059cbb],[-1]]"
        self.jobs = 1                          # corpus-parallel workers (-j)
        self.corpus_interleave = 0             # --corpus-interleave N: step N
        #   contracts' analyses round-robin in ONE process so their solve
        #   windows mix (MYTHRIL_TPU_CORPUS_INTERLEAVE overrides; 0 = off,
        #   1 = the sequential baseline with the same per-origin isolation)
        self.trace = None                      # --trace PATH (span tracer
        #   Perfetto export; MYTHRIL_TPU_TRACE is the env equivalent)
        self.heartbeat = None                  # --heartbeat PATH (live JSONL
        #   metrics stream; MYTHRIL_TPU_HEARTBEAT is the env equivalent,
        #   MYTHRIL_TPU_HEARTBEAT_INTERVAL the cadence)
        self.inject_fault = None               # --inject-fault SPEC (chaos
        #   harness; MYTHRIL_TPU_FAULTS is the env equivalent —
        #   resilience/faults.py grammar site:kind:trigger,...)

    def reset(self):
        self.__init__()


args = _Args()

"""The central solve path (reference mythril/support/model.py:63-125).

get_model(constraints, ...) is the single choke point every reachability
check and exploit concretization goes through:

  memory result tier -> quick-sat probe over recent models -> persistent
  disk tier (mythril_tpu/service/store.py, keyed by the blasted instance's
  content fingerprint, replay-verified) -> full solve with a deadline
  capped by the global time budget -> cache the verdict into every
  enabled tier.

raises UnsatError on unsat, SolverTimeOutException on unknown.
This is also the designed backend seam: `args.solver_backend` selects the
batched TPU solver for eligible queries (with the CPU CDCL as oracle).
"""

import os
import time
from collections import OrderedDict, deque
from contextlib import contextmanager, nullcontext
from typing import Iterable, List, Optional

from mythril_tpu.observe.tracer import span as trace_span
from mythril_tpu.smt.bitvec import Expression
from mythril_tpu.smt.model import Model
from mythril_tpu.smt.solver import Optimize, Solver
from mythril_tpu.smt.solver.frontend import (
    SAT,
    UNSAT,
    SolverTimeOutException,
    UnsatError,
)
from mythril_tpu.smt.solver.statistics import SolverStatistics
from mythril_tpu.support.args import args
from mythril_tpu.support.time_handler import time_handler

# UNSAT verdicts on the DETECTION path ("no vulnerability here") get a
# second opinion by default: the homegrown CDCL is the sole UNSAT authority
# in this z3-free environment, so detection-critical UNSATs are re-solved
# on a permuted instance (solver/sat_backend._crosscheck_unsat).
# MYTHRIL_TPU_UNSAT_CROSSCHECK=0 force-disables; =1 force-enables even on
# the engine path (the CI sweep). Engine-internal pruning UNSATs stay
# single-opinion by default — wrongly pruning a state costs coverage, not
# a false "safe" verdict on a module predicate, and crosschecking them
# would double the corpus wall.
_in_detection_context = False


@contextmanager
def detection_context():
    """Marks module predicate evaluation / issue confirmation; get_model
    requests the UNSAT crosscheck inside it."""
    global _in_detection_context
    previous = _in_detection_context
    _in_detection_context = True
    try:
        yield
    finally:
        _in_detection_context = previous


def _crosscheck_wanted() -> bool:
    env = os.environ.get("MYTHRIL_TPU_UNSAT_CROSSCHECK")
    if env == "0":
        return False
    if env not in (None, ""):
        return True
    return _in_detection_context

# When set to a list, every blasted query that reaches a real solve is
# recorded as (prep, status) — the multichip dryrun uses this to harvest
# production analyze-derived circuits and re-solve them on the device mesh
# (__graft_entry__.dryrun_multichip). Never set during normal runs.
capture_sink: Optional[List] = None


class ModelCache:
    """Recent models probed before any real solve
    (reference support_utils.py:57-68)."""

    def __init__(self, maxlen: int = 100):
        self.models = deque(maxlen=maxlen)

    def check_quick_sat(self, constraints) -> Optional[Model]:
        for model in self.models:
            if model.satisfies(constraints):
                return model
        return None

    def put(self, model: Model) -> None:
        self.models.appendleft(model)


model_cache = ModelCache()

_result_cache: "OrderedDict" = OrderedDict()
# entries now pin whole constraint-term DAGs (keys are Term tuples verified
# by structural equality), so keep the cap modest to bound retention
_RESULT_CACHE_MAX = 2 ** 12

# per-origin memory tiers (interleaved corpus driver): each contract's
# analysis gets its OWN term-keyed result cache and quick-sat deque, so
# per-contract verdicts AND witness models are independent of which
# sibling contracts shared the process and in what order — the
# cross-contract reuse boundary is the content-addressed persistent
# tier, never these. The interleave context installs an origin's pair
# into the module globals for the ambient call sites (get_model, the
# engine's direct quick-sat probes); get_models_batch resolves PER
# QUERY because one mixed window flush carries several origins' queries
# under a single baton holder.
_origin_caches: dict = {}


def caches_for_origin(origin):
    """(result cache, model cache) for `origin`; the module globals
    (whatever is currently installed) for origin-less traffic."""
    if origin is None:
        return _result_cache, model_cache
    if origin not in _origin_caches:
        _origin_caches[origin] = (OrderedDict(), ModelCache())
    return _origin_caches[origin]

# fingerprint -> origin tag of the analysis that FIRST persisted the
# entry this process (interleaved corpus driver; entries written outside
# an origin context are not recorded). Purely telemetry: a later hit
# from a DIFFERENT origin counts xcontract_dedup_hits — the disk tier's
# content-addressed fingerprints deduping identical (sub-)cones across
# contracts. First-writer-wins and size-capped; never consulted for
# verdicts (the replay-verification net is what makes hits safe).
_fingerprint_origins: dict = {}
_FINGERPRINT_ORIGIN_MAX = 1 << 16


def _record_fingerprint_origin(fingerprint, origin) -> None:
    if fingerprint is None or origin is None:
        return
    if fingerprint not in _fingerprint_origins \
            and len(_fingerprint_origins) >= _FINGERPRINT_ORIGIN_MAX:
        return
    _fingerprint_origins.setdefault(fingerprint, origin)


def _count_xcontract_hit(fingerprint, origin, stats) -> None:
    """A persistent-tier hit whose entry was recorded by a DIFFERENT
    origin this process — cross-contract dedup, counted."""
    stored = _fingerprint_origins.get(fingerprint)
    if stored is not None and origin is not None and stored != origin:
        stats.add_xcontract_dedup_hit()


def _cache_key(terms_list) -> Optional[tuple]:
    """Order- and multiplicity-insensitive key: the DEDUPLICATED constraint
    terms sorted by hash. Constraint-list concatenation routinely repeats
    terms ([a, a] vs [a] — same conjunction), so duplicates are dropped
    before sorting and both spellings share one cache entry.

    The stored entry is verified by structural equality on lookup
    (Term.__eq__), so a hash collision between different constraint sets
    cannot alias their sat/unsat verdicts (round-2 verdict weak #6; the
    reference caches by constraint-tuple equality, support/model.py:63)."""
    try:
        return tuple(sorted(dict.fromkeys(terms_list), key=hash))
    except TypeError:
        return None


# -- solve-service glue (mythril_tpu/service/) ------------------------------


def _memory_tier_enabled() -> bool:
    from mythril_tpu.service import memory_tier_enabled

    return memory_tier_enabled()


def _persistent_store():
    """The on-disk result store, or None when the disk tier is off."""
    from mythril_tpu.service import disk_tier_enabled

    if not disk_tier_enabled():
        return None
    from mythril_tpu.service.store import get_result_store

    store = get_result_store()
    return store if store.available else None


def _prep_partition(prep):
    """The AIG-level partition of a prepared instance's rewritten cone
    (preanalysis/aig_partition.py), or None for monolithic instances —
    the same gate the router's component dispatch uses."""
    aig_roots = getattr(prep, "aig_roots", None)
    if not aig_roots:
        return None
    try:
        from mythril_tpu.preanalysis import aig_partition

        return aig_partition.partition_for_aig_roots(aig_roots)
    except Exception:
        return None


def _count_net_tier(store, stats, event: str) -> None:
    """Mirror a persistent-tier event into the net_tier_* counters when
    the store is the fleet-shared NETWORK tier (fleet/netstore.py) —
    cross-process serving must be visible separately from a private
    local disk tier."""
    if stats is None or not getattr(store, "is_network", False):
        return
    if event == "hit":
        stats.add_net_tier_hit()
    elif event == "store":
        stats.add_net_tier_store()
    elif event == "reject":
        stats.add_net_tier_verify_reject()


def _probe_component_assembly(store, solver, prep, stats, origin=None):
    """Disk-tier probe at COMPONENT granularity: when the monolithic
    fingerprint misses but every non-trivial component of the partitioned
    instance has a stored SAT sub-model, the components reassemble into a
    full model — so a sub-cone shared by different parent queries hits
    across them. The recomposed assignment goes through Solver._reconstruct
    (validated against the ORIGINAL constraints) exactly like a monolithic
    replay: any staleness or collision degrades to a safe miss. Returns
    the ("sat", Model, True) outcome or None."""
    partition = _prep_partition(prep)
    if partition is None:
        return None
    from mythril_tpu.preanalysis.aig_partition import (
        apply_trivial_assignment,
        component_vars,
        merge_component_bits,
    )
    from mythril_tpu.service.fingerprint import component_fingerprint

    aig, dense_q = prep.aig_roots[0], prep.aig_roots[2]
    merged = [False] * (prep.num_vars + 1)
    # dedup attribution is deferred until the WHOLE assembly serves: a
    # later component missing (or the merged model failing replay
    # validation) means the probe served nothing, and counting the
    # partial hits would inflate a trended bench metric
    hit_fingerprints = []
    try:
        for component in partition.components:
            if apply_trivial_assignment(component, dense_q, merged):
                continue
            comp_nv, comp_cnf, comp_dense = component.instance(aig)
            fingerprint = component_fingerprint(
                comp_nv, comp_cnf, component.roots, comp_dense)
            entry = store.lookup(fingerprint)
            if entry is None or entry.verdict != "sat" \
                    or entry.num_vars != comp_nv or entry.bits is None:
                return None
            hit_fingerprints.append(fingerprint)
            merge_component_bits(
                comp_dense, dense_q, component_vars(comp_dense),
                entry.bits, merged)
        model = solver._reconstruct(prep, merged)
    except Exception:
        stats.add_persistent_verify_reject()
        _count_net_tier(store, stats, "reject")
        return None
    for fingerprint in hit_fingerprints:
        _count_xcontract_hit(fingerprint, origin, stats)
    return ("sat", model, True)


def _persist_component_entries(store, prep, bits, stats,
                               origin=None) -> None:
    """Store each non-trivial component's sub-model under its own
    fingerprint so later queries sharing the sub-cone (under any parent)
    can reassemble it from disk."""
    partition = _prep_partition(prep)
    if partition is None or bits is None:
        return
    from mythril_tpu.preanalysis.aig_partition import component_vars
    from mythril_tpu.service.fingerprint import component_fingerprint

    aig, dense_q = prep.aig_roots[0], prep.aig_roots[2]
    try:
        for component in partition.components:
            if component.trivial_assignment is not None:
                continue  # units reassemble for free; nothing to store
            comp_nv, comp_cnf, comp_dense = component.instance(aig)
            comp_bits = [False] * (comp_nv + 1)
            for gvar in component_vars(comp_dense):
                qvar = dense_q.get(int(gvar))
                if qvar is not None and qvar < len(bits):
                    comp_bits[comp_dense.arr[gvar]] = bool(bits[qvar])
            fingerprint = component_fingerprint(
                comp_nv, comp_cnf, component.roots, comp_dense)
            if store.store_sat(fingerprint, comp_nv, comp_bits):
                stats.add_persistent_store()
                _count_net_tier(store, stats, "store")
            _record_fingerprint_origin(fingerprint, origin)
    except Exception:
        pass  # persistence is best-effort; never break a solve


def _probe_persistent(solver, prep, crosscheck, stats, origin=None):
    """Disk-tier lookup for a blasted instance.

    Returns (fingerprint, outcome): outcome is ("sat", Model, True) /
    ("unsat", None, memoizable) on a trusted hit, None on a miss;
    fingerprint is None when the disk tier is off or the instance cannot
    be fingerprinted (callers reuse it to store the eventual verdict).

    A SAT entry is replay-verified: the stored assignment bits are pushed
    through Solver._reconstruct, which validates the rebuilt model against
    the ORIGINAL constraints — a fingerprint collision or corrupted entry
    degrades to a safe miss, never a wrong verdict. An UNSAT entry is only
    trusted on the detection path when it carries crosscheck provenance;
    an UNprovenanced entry trusted on the engine path must NOT be
    memoized into the memory tier (memoizable=False) — a memory-tier
    UNSAT is final even in a detection context, which would silently
    bypass the provenance gate for the rest of the process."""
    store = _persistent_store()
    if store is None:
        return None, None
    from mythril_tpu.service.fingerprint import instance_fingerprint

    with trace_span("cache.probe", cat="service"):
        return _probe_persistent_store(
            store, instance_fingerprint(prep), solver, prep, crosscheck,
            stats, origin=origin)


def _probe_persistent_store(store, fingerprint, solver, prep, crosscheck,
                            stats, origin=None):
    if fingerprint is None:
        return None, None
    entry = store.lookup(fingerprint)
    if entry is None:
        # monolithic miss: a partitioned instance may still reassemble
        # from per-component entries stored by different parent queries
        assembled = _probe_component_assembly(store, solver, prep, stats,
                                              origin=origin)
        stats.add_persistent_lookup(hit=assembled is not None)
        if assembled is not None:
            _count_net_tier(store, stats, "hit")
        return fingerprint, assembled
    if entry.verdict == "sat":
        if entry.num_vars != prep.num_vars:
            stats.add_persistent_verify_reject()
            _count_net_tier(store, stats, "reject")
            stats.add_persistent_lookup(hit=False)
            return fingerprint, None
        try:
            model = solver._reconstruct(prep, entry.bits)
        except Exception:
            stats.add_persistent_verify_reject()
            _count_net_tier(store, stats, "reject")
            stats.add_persistent_lookup(hit=False)
            return fingerprint, None
        stats.add_persistent_lookup(hit=True)
        _count_net_tier(store, stats, "hit")
        _count_xcontract_hit(fingerprint, origin, stats)
        return fingerprint, ("sat", model, True)
    if crosscheck and not entry.crosschecked:
        # detection-critical lookup, entry never got its second opinion:
        # re-solve (and re-store with provenance) instead of trusting it
        stats.add_persistent_lookup(hit=False)
        return fingerprint, None
    stats.add_persistent_lookup(hit=True)
    _count_net_tier(store, stats, "hit")
    _count_xcontract_hit(fingerprint, origin, stats)
    return fingerprint, ("unsat", None, entry.crosschecked)


def _crosscheck_confirmed(crosscheck: bool) -> bool:
    """Whether the just-settled UNSAT verdict's crosscheck actually RAN
    and positively re-proved UNSAT on the permuted instance.

    Provenance must record confirmed, not requested: a cap-skipped
    crosscheck (instance past CROSSCHECK_CLAUSE_CAP) or an inconclusive
    timed-out re-solve keeps the verdict in-process but must not be
    persisted as a second opinion — later detection-path runs would trust
    a never-netted verdict forever, on exactly the heaviest cones where a
    CDCL bug is most likely to hide. sat_backend records the outcome of
    the most recent crosscheck; read immediately after the settle."""
    if not crosscheck:
        return False
    from mythril_tpu.smt.solver import sat_backend

    return sat_backend.last_crosscheck_confirmed()


def _persist_result(fingerprint, prep, status, bits=None,
                    crosscheck=False, stats=None, origin=None) -> None:
    """Write a settled verdict into the disk tier (no-op when off)."""
    if fingerprint is None:
        return
    store = _persistent_store()
    if store is None:
        return
    if status == SAT:
        stored = store.store_sat(fingerprint, prep.num_vars, bits)
        _persist_component_entries(store, prep, bits, stats,
                                   origin=origin)
    elif status == UNSAT:
        stored = store.store_unsat(
            fingerprint, crosschecked=_crosscheck_confirmed(crosscheck))
    else:
        return
    _record_fingerprint_origin(fingerprint, origin)
    if stored and stats is not None:
        stats.add_persistent_store()
        _count_net_tier(store, stats, "store")


def get_model(
    constraints,
    minimize: Iterable = (),
    maximize: Iterable = (),
    enforce_execution_time: bool = True,
    solver_timeout: Optional[int] = None,
) -> Model:
    """Solve `constraints` (list of Bool); returns a validated Model."""
    with trace_span("solver.get_model", cat="solver",
                    constraints=len(constraints)):
        return _get_model_impl(constraints, minimize, maximize,
                               enforce_execution_time, solver_timeout)


def _get_model_impl(
    constraints,
    minimize: Iterable = (),
    maximize: Iterable = (),
    enforce_execution_time: bool = True,
    solver_timeout: Optional[int] = None,
) -> Model:
    minimize, maximize = tuple(minimize), tuple(maximize)
    raw_constraints: List = [
        c.raw if isinstance(c, Expression) else c for c in constraints
    ]

    timeout_ms = solver_timeout if solver_timeout is not None else args.solver_timeout
    timeout_s = timeout_ms / 1000.0
    if enforce_execution_time:
        timeout_s = min(timeout_s, max(time_handler.time_remaining() - 0.5, 0.05))

    crosscheck = _crosscheck_wanted()
    stats = SolverStatistics()
    key = None
    if not minimize and not maximize:
        key = _cache_key(raw_constraints) if _memory_tier_enabled() else None
        if key is not None and key in _result_cache:
            cached = _result_cache[key]
            stats.add_memory_hit()
            if isinstance(cached, Model):
                return cached
            # cached UNSAT is final even in a detection context: it came
            # from a completed CDCL solve this process, and re-solving it
            # (a full-timeout repeat) made wall-clock-sensitive timeouts
            # flip settled verdicts to UNKNOWN on loaded hosts
            raise UnsatError()
        quick = model_cache.check_quick_sat(raw_constraints)
        if quick is not None:
            stats.add_quick_sat_hit()
            if key is not None:
                # memoize the probe hit under the term key: without this
                # the same constraint set re-scans the model deque on
                # every call
                _store_result(key, quick)
            return quick

    if minimize or maximize:
        solver: Solver = Optimize(timeout=timeout_s)
        for m in minimize:
            solver.minimize(m.raw if isinstance(m, Expression) else m)
        for m in maximize:
            solver.maximize(m.raw if isinstance(m, Expression) else m)
        solver.unsat_crosscheck = crosscheck
        solver.add(raw_constraints)
        status = solver.check()
        if capture_sink is not None and getattr(solver, "last_prep", None):
            capture_sink.append((solver.last_prep, status))
        if status == SAT:
            return solver.model()
        if status == UNSAT:
            raise UnsatError()
        raise SolverTimeOutException()

    # plain (cacheable) path: prepare first so the disk tier can be probed
    # by the blasted instance's content fingerprint before any real solve
    solver = Solver(timeout=timeout_s)
    solver.unsat_crosscheck = crosscheck
    solver.add(raw_constraints)
    start = time.monotonic()
    try:
        prep = solver._prepare([])
        if prep.trivial is not None:
            if prep.trivial == SAT:
                model = solver._trivial_model(prep)
                if key is not None:
                    _store_result(key, model)
                    # feed the quick-sat probe deque too (the pre-service
                    # SAT tail did): trivial models often satisfy sibling
                    # queries with different keys
                    model_cache.put(model)
                return model
            if prep.trivial == UNSAT:
                if key is not None:
                    _store_result(key, UNSAT)
                raise UnsatError()
            raise SolverTimeOutException()

        from mythril_tpu.service.interleave import current_origin

        origin = current_origin()
        fingerprint, cached_outcome = _probe_persistent(
            solver, prep, crosscheck, stats, origin=origin)
        if cached_outcome is not None:
            verdict, model, memoizable = cached_outcome
            if verdict == "sat":
                if key is not None:
                    _store_result(key, model)
                model_cache.put(model)
                return model
            if key is not None and memoizable:
                _store_result(key, UNSAT)
            raise UnsatError()

        status = solver._solve_prepared(prep)
        if capture_sink is not None:
            capture_sink.append((prep, status))
        if status == SAT:
            model = solver.model()
            if key is not None:
                _store_result(key, model)
                model_cache.put(model)
            _persist_result(fingerprint, prep, SAT, bits=prep.last_bits,
                            crosscheck=crosscheck, stats=stats,
                            origin=origin)
            return model
        if status == UNSAT:
            if key is not None:
                _store_result(key, UNSAT)
            _persist_result(fingerprint, prep, UNSAT,
                            crosscheck=crosscheck, stats=stats,
                            origin=origin)
            raise UnsatError()
        raise SolverTimeOutException()
    finally:
        stats.add_query(time.monotonic() - start)


def get_models_batch(
    constraint_sets,
    enforce_execution_time: bool = True,
    solver_timeout: Optional[int] = None,
    crosscheck: Optional[bool] = None,
    fork_pairs=None,
    origins=None,
) -> List:
    """Batched multi-query solve — THE production device fan-out.

    Takes N constraint lists (sibling-path feasibility checks: drained
    pending states, fork sides of one exec iteration, detection-module
    confirmation pre-filters) and returns N entries of ("sat", Model) /
    ("unsat", None) / ("unknown", None).

    Pipeline: result-cache + quick-sat probe per query on host; every
    remaining eligible query is lowered/blasted and routed by the adaptive
    query router (tpu/router.py) — tiny cones host-direct, the rest
    level-bucketed into padded device dispatches under a host-fallback
    deadline; leftovers (device miss, cap reject, router deadline) are
    settled by the CDCL, which alone proves UNSAT.

    `crosscheck` requests the permuted-instance UNSAT second opinion on
    the CDCL settling pass (None = follow the ambient detection context,
    same policy as get_model).

    `fork_pairs` — (i, j) index pairs into `constraint_sets` marking the
    taken/fall-through sides of one batched JUMPI fork (the frontier's
    fork bundle): forwarded to the router so a pair whose blasted cones
    still share their base roots packs ONCE and rides one ragged stream
    with the fork literals as extra assumption roots. Purely a routing
    hint — verdicts, caching, and the CDCL UNSAT oracle are untouched.

    `origins` — per-query origin tags (contract identity, from the
    interleaved corpus driver's coalescing window; None entries for
    untagged traffic). Telemetry + routing hints only: the router
    counts mixed-origin ragged streams (xcontract_windows) and orders
    the window so streams actually mix; the persistent tier attributes
    stored entries so cross-contract reuse is countable. Verdicts and
    demux are index-based and untouched by tags.
    """
    with trace_span("solver.batch", cat="solver",
                    queries=len(constraint_sets)):
        return _get_models_batch_impl(constraint_sets,
                                      enforce_execution_time,
                                      solver_timeout, crosscheck,
                                      fork_pairs=fork_pairs,
                                      origins=origins)


def _get_models_batch_impl(
    constraint_sets,
    enforce_execution_time: bool = True,
    solver_timeout: Optional[int] = None,
    crosscheck: Optional[bool] = None,
    fork_pairs=None,
    origins=None,
) -> List:
    from mythril_tpu.smt.solver.frontend import Solver

    stats = SolverStatistics()
    results: List = [None] * len(constraint_sets)
    if crosscheck is None:
        crosscheck = _crosscheck_wanted()

    timeout_ms = solver_timeout if solver_timeout is not None else args.solver_timeout
    timeout_s = timeout_ms / 1000.0
    if enforce_execution_time:
        timeout_s = min(timeout_s, max(time_handler.time_remaining() - 0.5, 0.05))

    use_memory_tier = _memory_tier_enabled()
    from mythril_tpu.service.interleave import blaster_scope, current_origin

    if origins is None:
        ambient = current_origin()
        origins = [ambient] * len(constraint_sets)

    def origin_of(index):
        return origins[index] if index < len(origins) else None

    # fork-pair members prepare under the root-forcing-deferred aig_opt
    # sweep (preanalysis/aig_opt.deferred_forcing): the per-side forced
    # constant sweep diverges the pair's shared base roots, which is
    # exactly what the router's shared-cone pair packing keys on. Gated
    # on the pair actually being able to reach the ragged fork lane —
    # elsewhere the forced sweep's smaller CDCL cones win.
    fork_members = set()
    if fork_pairs:
        try:
            from mythril_tpu.tpu.router import ragged_enabled

            if args.solver_backend == "tpu" and ragged_enabled():
                for pair in fork_pairs:
                    fork_members.update(pair)
        except Exception:
            fork_members = set()

    pending: List[tuple] = []  # (idx, key, fingerprint, solver, prep)
    start = time.monotonic()
    for idx, constraints in enumerate(constraint_sets):
        raw_constraints = [
            c.raw if isinstance(c, Expression) else c for c in constraints
        ]
        # per-QUERY cache resolution: one mixed window flush solves
        # several origins' queries under a single caller, so the module
        # globals (the flusher's origin) would file sibling contracts'
        # results — and later serve their witness models — into the
        # wrong contract's tiers
        tier, quick_cache = caches_for_origin(origin_of(idx))
        key = _cache_key(raw_constraints) if use_memory_tier else None
        if key is not None and key in tier:
            cached = tier[key]
            stats.add_memory_hit()
            results[idx] = (
                ("sat", cached) if isinstance(cached, Model) else ("unsat", None)
            )
            continue
        quick = quick_cache.check_quick_sat(raw_constraints)
        if quick is not None:
            stats.add_quick_sat_hit()
            if key is not None:
                # memoize the probe hit (same policy as get_model): the
                # next lookup hits the term-keyed tier, not a deque scan
                _store_result(key, quick, tier)
            results[idx] = ("sat", quick)
            continue
        solver = Solver(timeout=timeout_s)
        solver.add(raw_constraints)
        # per-query blaster scope: a mixed window flush prepares several
        # origins' queries under one baton holder — each must blast into
        # ITS contract's private AIG (id-space isolation is what keeps
        # witness models schedule-independent)
        if idx in fork_members:
            from mythril_tpu.preanalysis import aig_opt

            prep_scope = aig_opt.deferred_forcing()
        else:
            prep_scope = nullcontext()
        with blaster_scope(origin_of(idx)), prep_scope:
            prep = solver._prepare([])
        if prep.trivial is not None:
            if prep.trivial == SAT:
                # preprocessing may have eliminated every constraint via
                # substitutions — the model must still carry those values
                model = solver._trivial_model(prep)
                results[idx] = ("sat", model)
                if key is not None:
                    _store_result(key, model, tier)
            elif prep.trivial == UNSAT:
                results[idx] = ("unsat", None)
                if key is not None:
                    _store_result(key, UNSAT, tier)
            else:
                results[idx] = ("unknown", None)
            continue
        fingerprint, cached_outcome = _probe_persistent(
            solver, prep, crosscheck, stats, origin=origin_of(idx))
        if cached_outcome is not None:
            verdict, model, memoizable = cached_outcome
            if verdict == "sat":
                results[idx] = ("sat", model)
                if key is not None:
                    _store_result(key, model, tier)
                quick_cache.put(model)
            else:
                results[idx] = ("unsat", None)
                if key is not None and memoizable:
                    _store_result(key, UNSAT, tier)
            continue
        pending.append((idx, key, fingerprint, solver, prep))

    if pending and args.solver_backend == "tpu":
        eligible = []
        ineligible = []
        for entry in pending:
            prep = entry[4]
            has_empty = (
                prep.clauses.has_empty
                if hasattr(prep.clauses, "has_empty")
                else any(len(c) == 0 for c in prep.clauses)
            )
            if prep.blaster is not None and not has_empty:
                eligible.append(entry)
            else:
                ineligible.append(entry)
                stats.add_device_ineligible()
        try:
            from mythril_tpu.tpu.router import get_router

            # the adaptive router owns the device decision: calibrated
            # caps, tiny-cone host shortcut, level-bucketed padded
            # dispatches, and a host-fallback deadline that always leaves
            # the CDCL settling pass a real window (tpu/router.py). The
            # justification-based circuit kernel remains the device path:
            # it searches over AIG inputs, so blasted arithmetic actually
            # solves (tpu/circuit.py).
            problems = [
                (p.num_vars, p.clauses, p.aig_roots)
                for _, _, _, _, p in eligible
            ]
            # remap fork pairs onto the eligible-problem axis: a pair
            # survives only when BOTH sides reached the router (host
            # tiers may have settled one side already)
            eligible_pairs = None
            if fork_pairs:
                position = {entry[0]: pos
                            for pos, entry in enumerate(eligible)}
                eligible_pairs = [
                    (position[i], position[j]) for i, j in fork_pairs
                    if i in position and j in position
                ] or None
            bits_list = get_router().dispatch(
                problems, timeout_s, stats, fork_pairs=eligible_pairs,
                origins=[origin_of(entry[0]) for entry in eligible])
        except Exception as error:
            import logging

            logging.getLogger(__name__).warning(
                "batched device solve failed (%s); CDCL fallback", error)
            bits_list = [None] * len(eligible)
        still_pending = list(ineligible)
        for (idx, key, fingerprint, solver, prep), bits in \
                zip(eligible, bits_list):
            stats.add_device_batch_query(hit=bits is not None)
            if bits is None:
                still_pending.append((idx, key, fingerprint, solver, prep))
                continue
            try:
                model = solver._reconstruct(prep, bits)
            except Exception:
                still_pending.append((idx, key, fingerprint, solver, prep))
                continue
            results[idx] = ("sat", model)
            tier, quick_cache = caches_for_origin(origin_of(idx))
            if key is not None:
                _store_result(key, model, tier)
                quick_cache.put(model)
            _persist_result(fingerprint, prep, SAT, bits=bits,
                            crosscheck=crosscheck, stats=stats,
                            origin=origin_of(idx))
        pending = still_pending

    # CDCL settles the rest (and proves UNSAT); plain path, no device re-entry
    settle_start = time.monotonic()
    for idx, key, fingerprint, solver, prep in pending:
        solver.allow_device = False
        solver.unsat_crosscheck = crosscheck
        solver.timeout = max(0.05, timeout_s - (time.monotonic() - start))
        status = solver._solve_prepared(prep)
        if capture_sink is not None:
            capture_sink.append((prep, status))
        tier, quick_cache = caches_for_origin(origin_of(idx))
        if status == SAT:
            model = solver.model()
            results[idx] = ("sat", model)
            if key is not None:
                _store_result(key, model, tier)
                quick_cache.put(model)
            _persist_result(fingerprint, prep, SAT, bits=prep.last_bits,
                            crosscheck=crosscheck, stats=stats,
                            origin=origin_of(idx))
        elif status == UNSAT:
            results[idx] = ("unsat", None)
            if key is not None:
                _store_result(key, UNSAT, tier)
            _persist_result(fingerprint, prep, UNSAT,
                            crosscheck=crosscheck, stats=stats,
                            origin=origin_of(idx))
        else:
            results[idx] = ("unknown", None)
    stats.add_host_route_seconds(time.monotonic() - settle_start)
    stats.add_batch(len(constraint_sets), time.monotonic() - start)
    return results


def _store_result(key, value, cache=None) -> None:
    target = cache if cache is not None else _result_cache
    target[key] = value
    while len(target) > _RESULT_CACHE_MAX:
        target.popitem(last=False)


def clear_caches(session: Optional[str] = None) -> None:
    """Drop solve-cache state. With `session` given, the eviction is
    SESSION-SCOPED (the serve daemon's per-tenant invalidation): only
    that tenant's origins lose their memory tiers, quick-sat deques,
    private blasters, and prefix snapshots — the shared session strash
    table, the disk tier, the scheduler, other tenants' warmth, and the
    resilience fuses are untouched, so one tenant's invalidation cannot
    cold-start every other tenant. Without `session`, everything clears
    (the historical all-or-nothing behavior tests and workers rely on)."""
    if session is not None:
        from mythril_tpu.service import tenancy

        tenancy.evict_session(session)
        return
    _result_cache.clear()
    model_cache.models.clear()
    _origin_caches.clear()
    _fingerprint_origins.clear()
    # per-origin private blasters (service/tenancy.py): a full clear
    # drops every tenant's AIG — the serve daemon's warm tiers do not
    # survive a process-wide clear, only session-scoped eviction is
    # selective
    from mythril_tpu.service import tenancy

    tenancy.clear_blasters()
    # service layer: buffered scheduler state is discarded and the
    # persistent-store handle released, so tests and --jobs workers start
    # clean — a cleared process re-populates from disk, not stale memory
    from mythril_tpu.service import reset_service_state

    reset_service_state()
    # incremental prepare layer: prefix snapshots and the session strash
    # table key on term/AIG identity — stale-generation entries must never
    # resolve against a rebuilt term graph
    from mythril_tpu.preanalysis import aig_opt
    from mythril_tpu.smt.solver import incremental

    incremental.reset()
    aig_opt.reset_cache()
    # fault containment: session fuses (disable-for-session degradations)
    # are per-run state — a cleared process gets its optional stages back
    from mythril_tpu import resilience

    resilience.reset_session()

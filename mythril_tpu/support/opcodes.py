"""EVM opcode metadata table (through Cancun).

Behavioral parity with the reference opcode registry
(mythril/support/opcodes.py:15, mythril/laser/ethereum/instruction_data.py),
re-expressed as a byte-indexed spec table. Gas values are (min, max) bounds
used for symbolic gas accounting; stack arity drives pre-dispatch underflow
checks (reference svm.py:423-434).
"""

from typing import Dict, NamedTuple, Optional


class OpSpec(NamedTuple):
    byte: int
    name: str
    pops: int
    pushes: int
    gas_min: int
    gas_max: int


def _spec(byte, name, pops, pushes, gas_min, gas_max=None):
    return OpSpec(byte, name, pops, pushes, gas_min,
                  gas_max if gas_max is not None else gas_min)


_RAW = [
    # byte, name, pops, pushes, gas_min[, gas_max]
    (0x00, "STOP", 0, 0, 0),
    (0x01, "ADD", 2, 1, 3),
    (0x02, "MUL", 2, 1, 5),
    (0x03, "SUB", 2, 1, 3),
    (0x04, "DIV", 2, 1, 5),
    (0x05, "SDIV", 2, 1, 5),
    (0x06, "MOD", 2, 1, 5),
    (0x07, "SMOD", 2, 1, 5),
    (0x08, "ADDMOD", 3, 1, 8),
    (0x09, "MULMOD", 3, 1, 8),
    (0x0A, "EXP", 2, 1, 10, 10 + 50 * 32),  # 10 + 50/byte of exponent
    (0x0B, "SIGNEXTEND", 2, 1, 5),
    (0x10, "LT", 2, 1, 3),
    (0x11, "GT", 2, 1, 3),
    (0x12, "SLT", 2, 1, 3),
    (0x13, "SGT", 2, 1, 3),
    (0x14, "EQ", 2, 1, 3),
    (0x15, "ISZERO", 1, 1, 3),
    (0x16, "AND", 2, 1, 3),
    (0x17, "OR", 2, 1, 3),
    (0x18, "XOR", 2, 1, 3),
    (0x19, "NOT", 1, 1, 3),
    (0x1A, "BYTE", 2, 1, 3),
    (0x1B, "SHL", 2, 1, 3),
    (0x1C, "SHR", 2, 1, 3),
    (0x1D, "SAR", 2, 1, 3),
    (0x20, "SHA3", 2, 1, 30, 30 + 6 * 8),
    (0x30, "ADDRESS", 0, 1, 2),
    (0x31, "BALANCE", 1, 1, 100, 2600),
    (0x32, "ORIGIN", 0, 1, 2),
    (0x33, "CALLER", 0, 1, 2),
    (0x34, "CALLVALUE", 0, 1, 2),
    (0x35, "CALLDATALOAD", 1, 1, 3),
    (0x36, "CALLDATASIZE", 0, 1, 2),
    (0x37, "CALLDATACOPY", 3, 0, 2, 2 + 3 * 768),
    (0x38, "CODESIZE", 0, 1, 2),
    (0x39, "CODECOPY", 3, 0, 2, 2 + 3 * 768),
    (0x3A, "GASPRICE", 0, 1, 2),
    (0x3B, "EXTCODESIZE", 1, 1, 100, 2600),
    (0x3C, "EXTCODECOPY", 4, 0, 100, 2600 + 3 * 768),
    (0x3D, "RETURNDATASIZE", 0, 1, 2),
    (0x3E, "RETURNDATACOPY", 3, 0, 2, 2 + 3 * 768),
    (0x3F, "EXTCODEHASH", 1, 1, 100, 2600),
    (0x40, "BLOCKHASH", 1, 1, 20),
    (0x41, "COINBASE", 0, 1, 2),
    (0x42, "TIMESTAMP", 0, 1, 2),
    (0x43, "NUMBER", 0, 1, 2),
    (0x44, "PREVRANDAO", 0, 1, 2),
    (0x45, "GASLIMIT", 0, 1, 2),
    (0x46, "CHAINID", 0, 1, 2),
    (0x47, "SELFBALANCE", 0, 1, 5),
    (0x48, "BASEFEE", 0, 1, 2),
    (0x49, "BLOBHASH", 1, 1, 3),
    (0x4A, "BLOBBASEFEE", 0, 1, 2),
    (0x50, "POP", 1, 0, 2),
    (0x51, "MLOAD", 1, 1, 3, 96),
    (0x52, "MSTORE", 2, 0, 3, 98),
    (0x53, "MSTORE8", 2, 0, 3, 98),
    (0x54, "SLOAD", 1, 1, 100, 2100),
    (0x55, "SSTORE", 2, 0, 100, 22100),
    (0x56, "JUMP", 1, 0, 8),
    (0x57, "JUMPI", 2, 0, 10),
    (0x58, "PC", 0, 1, 2),
    (0x59, "MSIZE", 0, 1, 2),
    (0x5A, "GAS", 0, 1, 2),
    (0x5B, "JUMPDEST", 0, 0, 1),
    (0x5C, "TLOAD", 1, 1, 100),
    (0x5D, "TSTORE", 2, 0, 100),
    (0x5E, "MCOPY", 3, 0, 3, 3 + 3 * 768),
    (0x5F, "PUSH0", 0, 1, 2),
    (0xA0, "LOG0", 2, 0, 375, 375 + 8 * 32),
    (0xA1, "LOG1", 3, 0, 750, 750 + 8 * 32),
    (0xA2, "LOG2", 4, 0, 1125, 1125 + 8 * 32),
    (0xA3, "LOG3", 5, 0, 1500, 1500 + 8 * 32),
    (0xA4, "LOG4", 6, 0, 1875, 1875 + 8 * 32),
    (0xF0, "CREATE", 3, 1, 32000, 32000 + 200 * 24576),
    (0xF1, "CALL", 7, 1, 100, 2600 + 9000 + 25000),
    (0xF2, "CALLCODE", 7, 1, 100, 2600 + 9000),
    (0xF3, "RETURN", 2, 0, 0),
    (0xF4, "DELEGATECALL", 6, 1, 100, 2600),
    (0xF5, "CREATE2", 4, 1, 32000, 32000 + 200 * 24576 + 6 * 768),
    (0xFA, "STATICCALL", 6, 1, 100, 2600),
    (0xFD, "REVERT", 2, 0, 0),
    (0xFE, "INVALID", 0, 0, 0),
    (0xFF, "SELFDESTRUCT", 1, 0, 5000, 5000 + 25000),
]

BY_BYTE: Dict[int, OpSpec] = {}
BY_NAME: Dict[str, OpSpec] = {}

for row in _RAW:
    spec = _spec(*row)
    BY_BYTE[spec.byte] = spec
    BY_NAME[spec.name] = spec

# PUSH1..PUSH32 (0x60..0x7F)
for width in range(1, 33):
    spec = _spec(0x5F + width, f"PUSH{width}", 0, 1, 3)
    BY_BYTE[spec.byte] = spec
    BY_NAME[spec.name] = spec

# DUP1..DUP16 (0x80..0x8F): DUPn pops n, pushes n+1 (net +1, needs n on stack)
for depth in range(1, 17):
    spec = _spec(0x7F + depth, f"DUP{depth}", depth, depth + 1, 3)
    BY_BYTE[spec.byte] = spec
    BY_NAME[spec.name] = spec

# SWAP1..SWAP16 (0x90..0x9F): SWAPn needs n+1 on stack
for depth in range(1, 17):
    spec = _spec(0x8F + depth, f"SWAP{depth}", depth + 1, depth + 1, 3)
    BY_BYTE[spec.byte] = spec
    BY_NAME[spec.name] = spec

# The detection layer hooks "ASSERT_FAIL" for the solidity 0.8 panic opcode;
# 0xFE is rendered as ASSERT_FAIL to match reference report vocabulary.
ASSERT_FAIL_NAME = "ASSERT_FAIL"


def spec_for_byte(byte: int) -> Optional[OpSpec]:
    return BY_BYTE.get(byte)


def name_of(byte: int) -> str:
    spec = BY_BYTE.get(byte)
    return spec.name if spec else f"UNKNOWN_0x{byte:02x}"


def push_width(name: str) -> int:
    """Operand byte count for PUSHn; 0 for anything else (incl. PUSH0)."""
    if name.startswith("PUSH") and name != "PUSH0":
        return int(name[4:])
    return 0


def required_stack(name: str) -> int:
    return BY_NAME[name].pops if name in BY_NAME else 0

"""On-disk configuration tier (reference mythril/mythril/mythril_config.py:16).

Bootstraps the `~/.mythril` data directory (override with MYTHRIL_DIR) and
`config.ini`, and resolves the RPC endpoint from, in priority order:
CLI --rpc flag > INFURA_ID env > config.ini `dynamic_loading`."""

import codecs
import logging
import os
from configparser import ConfigParser
from typing import Optional

from mythril_tpu.support.lock import LockFile

log = logging.getLogger(__name__)


class MythrilConfig:
    def __init__(self):
        self.infura_id: Optional[str] = os.getenv("INFURA_ID")
        self.mythril_dir = self.init_mythril_dir()
        self.config_path = os.path.join(self.mythril_dir, "config.ini")
        self._init_config()
        self.eth = None

    @staticmethod
    def init_mythril_dir() -> str:
        mythril_dir = os.environ.get(
            "MYTHRIL_DIR", os.path.join(os.path.expanduser("~"), ".mythril")
        )
        if not os.path.exists(mythril_dir):
            log.info("creating mythril data directory %s", mythril_dir)
            os.makedirs(mythril_dir, exist_ok=True)
        return mythril_dir

    def _init_config(self) -> None:
        """Create config.ini with defaults on first run; read it after."""
        if not os.path.exists(self.config_path):
            log.info("no config file found, creating %s", self.config_path)
            open(self.config_path, "a").close()
        config = ConfigParser(allow_no_value=True)
        config.optionxform = str
        with LockFile(self.config_path + ".lock"):
            config.read(self.config_path, encoding="utf-8")
            changed = False
            if "defaults" not in config.sections():
                config.add_section("defaults")
                changed = True
            if not config.has_option("defaults", "dynamic_loading"):
                config.set(
                    "defaults",
                    "#- dynamic_loading: infura | HOST:PORT | off",
                    "",
                )
                config.set("defaults", "dynamic_loading", "infura")
                changed = True
            if not config.has_option("defaults", "infura_id"):
                config.set("defaults", "infura_id", "")
                changed = True
            if changed:
                with codecs.open(self.config_path, "w", "utf-8") as handle:
                    config.write(handle)
        if not self.infura_id:
            self.infura_id = config.get("defaults", "infura_id", fallback="")
        self.dynamic_loading = config.get(
            "defaults", "dynamic_loading", fallback="infura"
        )

    def set_api_rpc(self, rpc: Optional[str] = None, rpctls: bool = False):
        """Build the JSON-RPC client per the resolved endpoint."""
        from mythril_tpu.ethereum.interface.client import EthJsonRpc

        endpoint = rpc or self.dynamic_loading
        if endpoint in (None, "", "off"):
            self.eth = None
            return None
        self.eth = EthJsonRpc.from_cli(
            None if endpoint == "infura" else endpoint, rpctls
        )
        return self.eth

"""Static CNF preprocessing ahead of fingerprinting and dispatch.

Two passes over the blasted instance, both verdict-preserving:

  unit propagation      asserted unit clauses force assignments; satisfied
                        clauses are dropped and falsified literals deleted,
                        to a fixpoint. Equivalence-preserving: every forced
                        assignment is a logical consequence, and each one
                        is RE-ASSERTED as a unit clause in the output so
                        any model of the simplified CNF assigns it
                        correctly (model replay / reconstruction stays
                        valid against the original constraints).
  pure-literal rule     a variable occurring with a single polarity among
                        the live clauses is pinned to that polarity (unit
                        clause added, its clauses dropped). Preserves
                        SAT/UNSAT and every surviving model satisfies the
                        original CNF — but it CAN remove models, so the
                        caller must disable it (allow_pure=False) when the
                        instance will later be probed under assumptions
                        (Optimize bit fixing): pinning a bit the original
                        CNF leaves free would turn a SAT probe into UNSAT
                        and mis-minimize exploits.

Variable numbering is PRESERVED (no renumbering): downstream consumers —
dense var maps for objective bits, session assumptions, stored assignment
replay, model reconstruction — all keep working on the simplified
instance unchanged.

`split_components` additionally partitions an instance into variable-
disjoint connected components (each renumbered dense) so the CDCL settles
independent sub-cones separately; merged component models recompose into
a full-space assignment Solver._reconstruct accepts.

Everything here is total: any unexpected shape degrades to "no change",
never to a wrong CNF.
"""

from typing import List, Optional

import numpy as np

from mythril_tpu.smt.bitblast import CNF

# instances past this many clauses skip preprocessing: the passes are
# vectorized but still cost a few full-array sweeps per round, and cones
# this size are dominated by CDCL wall anyway
PREPROCESS_CLAUSE_CAP = 400_000
MAX_ROUNDS = 40
# component splitting runs scipy's native connected_components over the
# bipartite variable-clause incidence graph (~1 ms at the cap; a Python
# union-find measured 150+ ms there — too expensive for a decision that
# usually answers "one component, no split"); bounded both ways so the
# split decision never costs more than the solve it is trying to shrink
SPLIT_CLAUSE_CAP = 60_000
SPLIT_MIN_CLAUSES = 64


class PreprocessResult:
    __slots__ = ("cnf", "conflict", "units", "pures", "removed_clauses",
                 "changed")

    def __init__(self, cnf, conflict, units, pures, removed_clauses):
        self.cnf = cnf
        self.conflict = conflict
        self.units = units          # assignments forced by propagation
        self.pures = pures          # assignments chosen by the pure rule
        self.removed_clauses = removed_clauses
        self.changed = conflict or units > 0 or pures > 0 \
            or removed_clauses > 0


def _as_buffers(clauses):
    """(lits int64, offsets int64, n) view of either CNF buffers or a
    legacy clause list; None when empty/unconvertible."""
    if not hasattr(clauses, "lits"):
        try:
            clauses = CNF.from_clauses(list(clauses))
        except (TypeError, ValueError):
            return None
    if len(clauses) == 0:
        return None
    lits = np.asarray(clauses.lits, dtype=np.int64)
    offsets = np.asarray(clauses.offsets, dtype=np.int64)
    return lits, offsets, len(clauses)


def preprocess_cnf(num_vars: int, clauses,
                   allow_pure: bool = True) -> Optional[PreprocessResult]:
    """Simplify `clauses` (same variable numbering); None = not applicable
    (empty/oversize instance or nothing to do)."""
    buffers = _as_buffers(clauses)
    if buffers is None or num_vars <= 0:
        return None
    lits, offsets, n_clauses = buffers
    if n_clauses > PREPROCESS_CLAUSE_CAP:
        return None
    lengths = offsets[1:] - offsets[:-1]
    if (lengths == 0).any():
        # an already-empty clause: syntactic conflict
        return PreprocessResult(None, True, 0, 0, 0)
    var = np.abs(lits)
    if var.max(initial=0) > num_vars:
        return None  # malformed instance: leave it to the solver
    sign = np.sign(lits).astype(np.int8)
    clause_ids = np.repeat(np.arange(n_clauses, dtype=np.int64), lengths)

    assign = np.zeros(num_vars + 1, dtype=np.int8)  # 0 free, +1/-1 pinned
    forced_by_up = 0
    forced_by_pure = 0

    for _round in range(MAX_ROUNDS):
        lit_val = assign[var] * sign          # +1 true, -1 false, 0 free
        clause_sat = np.zeros(n_clauses, dtype=bool)
        np.logical_or.at(clause_sat, clause_ids, lit_val == 1)
        false_per_clause = np.zeros(n_clauses, dtype=np.int64)
        np.add.at(false_per_clause, clause_ids, lit_val == -1)
        eff_len = lengths - false_per_clause
        live = ~clause_sat
        if (live & (eff_len == 0)).any():
            return PreprocessResult(None, True, forced_by_up,
                                    forced_by_pure, 0)
        unit_mask = live & (eff_len == 1)
        progressed = False
        if unit_mask.any():
            pick = unit_mask[clause_ids] & (lit_val == 0)
            unit_vars = var[pick]
            unit_signs = sign[pick]
            # conflicting forcings in one round (x and -x both unit)
            order = np.argsort(unit_vars, kind="stable")
            uv, us = unit_vars[order], unit_signs[order]
            same = uv[1:] == uv[:-1]
            if (same & (us[1:] != us[:-1])).any():
                return PreprocessResult(None, True, forced_by_up,
                                        forced_by_pure, 0)
            before = int(np.count_nonzero(assign))
            assign[uv] = us
            forced_by_up += int(np.count_nonzero(assign)) - before
            progressed = True
        elif allow_pure:
            live_lit = live[clause_ids] & (lit_val == 0)
            pos = np.zeros(num_vars + 1, dtype=bool)
            neg = np.zeros(num_vars + 1, dtype=bool)
            np.logical_or.at(pos, var[live_lit & (sign == 1)], True)
            np.logical_or.at(neg, var[live_lit & (sign == -1)], True)
            pure = (pos ^ neg) & (assign == 0)
            pure[0] = False
            if pure.any():
                assign[pure & pos] = 1
                assign[pure & neg] = -1
                forced_by_pure += int(np.count_nonzero(pure))
                progressed = True
        if not progressed:
            break

    assigned = int(np.count_nonzero(assign))
    if assigned == 0:
        return None  # nothing learned; keep the original buffers

    # rebuild: live clauses minus falsified literals, plus one unit clause
    # per pinned variable (pins the model so replay/validation stays exact)
    lit_val = assign[var] * sign
    clause_sat = np.zeros(n_clauses, dtype=bool)
    np.logical_or.at(clause_sat, clause_ids, lit_val == 1)
    live = ~clause_sat
    keep_lit = live[clause_ids] & (lit_val == 0)
    kept_lits = lits[keep_lit]
    kept_counts = np.zeros(n_clauses, dtype=np.int64)
    np.add.at(kept_counts, clause_ids, keep_lit)
    kept_counts = kept_counts[live]

    pinned_vars = np.nonzero(assign)[0]
    unit_lits = pinned_vars * assign[pinned_vars]

    new_lits = np.concatenate([
        kept_lits, unit_lits.astype(np.int64)]).astype(np.int32)
    new_lengths = np.concatenate([
        kept_counts, np.ones(len(unit_lits), dtype=np.int64)])
    if len(kept_counts) and (kept_counts == 0).any():
        # a clause lost every literal after the rounds budget ran out with
        # forcings still pending: that is a conflict, not an empty clause
        return PreprocessResult(None, True, forced_by_up, forced_by_pure, 0)
    new_offsets = np.zeros(len(new_lengths) + 1, dtype=np.int64)
    np.cumsum(new_lengths, out=new_offsets[1:])
    new_cnf = CNF(new_lits, new_offsets, len(new_lengths), False)
    removed = n_clauses - int(np.count_nonzero(live))
    return PreprocessResult(new_cnf, False, forced_by_up, forced_by_pure,
                            removed)


class Component:
    """One variable-disjoint sub-instance, densely renumbered."""

    __slots__ = ("num_vars", "cnf", "orig_vars", "trivial_bits")

    def __init__(self, num_vars, cnf, orig_vars, trivial_bits=None):
        self.num_vars = num_vars    # local (dense) variable count
        self.cnf = cnf              # CNF in local numbering
        self.orig_vars = orig_vars  # local var i+1 -> orig_vars[i]
        # all-unit consistent components (preprocessing leaves one unit
        # clause per pinned var) carry their model directly — no solver
        # round-trip needed. Contradictory unit components deliberately do
        # NOT settle here: the CDCL must prove that UNSAT so the
        # detection-path crosscheck policy applies.
        self.trivial_bits = trivial_bits


def split_components(num_vars: int, clauses) -> Optional[List[Component]]:
    """Partition an instance into connected components (variables linked by
    sharing a clause). Returns None when the instance is one component,
    empty, or past SPLIT_CLAUSE_CAP."""
    buffers = _as_buffers(clauses)
    if buffers is None or num_vars <= 0:
        return None
    lits, offsets, n_clauses = buffers
    if n_clauses > SPLIT_CLAUSE_CAP or n_clauses < SPLIT_MIN_CLAUSES:
        return None
    if ((offsets[1:] - offsets[:-1]) == 0).any():
        return None  # empty clause: the solver's problem, not a split's
    var = np.abs(lits)
    if var.max(initial=0) > num_vars:
        return None
    from mythril_tpu.preanalysis.components import connected_labels

    lengths = offsets[1:] - offsets[:-1]
    clause_ids = np.repeat(np.arange(n_clauses, dtype=np.int64), lengths)
    # bipartite incidence: var nodes [0..num_vars], clause nodes after
    labels = connected_labels(
        num_vars + 1 + n_clauses, var, clause_ids + num_vars + 1)
    if labels is None:
        return None
    clause_label = labels[var[offsets[:-1]]]
    distinct = np.unique(clause_label)
    if len(distinct) < 2:
        return None

    components: List[Component] = []
    for root in distinct:
        clause_mask = clause_label == root
        lit_mask = clause_mask[clause_ids]
        comp_lits = lits[lit_mask]
        comp_vars = np.unique(np.abs(comp_lits))
        remap = np.zeros(num_vars + 1, dtype=np.int64)
        remap[comp_vars] = np.arange(1, len(comp_vars) + 1)
        local = np.sign(comp_lits) * remap[np.abs(comp_lits)]
        comp_lengths = lengths[clause_mask]
        comp_offsets = np.zeros(len(comp_lengths) + 1, dtype=np.int64)
        np.cumsum(comp_lengths, out=comp_offsets[1:])
        cnf = CNF(local.astype(np.int32), comp_offsets,
                  len(comp_lengths), False)
        trivial_bits = None
        if (comp_lengths == 1).all():
            signs = np.sign(local)
            order = np.argsort(np.abs(local), kind="stable")
            lv, ls = np.abs(local)[order], signs[order]
            contradictory = ((lv[1:] == lv[:-1])
                             & (ls[1:] != ls[:-1])).any()
            if not contradictory:
                trivial_bits = [False] * (len(comp_vars) + 1)
                for lit in local:
                    trivial_bits[abs(int(lit))] = lit > 0
        components.append(
            Component(len(comp_vars), cnf, comp_vars.tolist(),
                      trivial_bits=trivial_bits))
    return components


def merge_component_bits(num_vars: int, components: List[Component],
                         bits_per_component: List[List[bool]]) -> List[bool]:
    """Recompose per-component models into one full-space assignment
    (variables in no clause default to False, matching the CDCL's model
    completion)."""
    merged = [False] * (num_vars + 1)
    for component, bits in zip(components, bits_per_component):
        for local, orig in enumerate(component.orig_vars, start=1):
            merged[orig] = bool(bits[local])
    return merged

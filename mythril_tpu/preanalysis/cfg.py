"""Static CFG recovery over a Disassembly.

Basic blocks are cut at JUMPDESTs and after terminators; jump targets are
resolved by an abstract-stack dataflow pass (the TVM pattern of analysis
passes ahead of lowering): each block's transfer function tracks the
concrete values PUSH placed on the stack — through DUP/SWAP and arbitrary
pop/push arity of every other opcode — and the per-block input stacks are
joined to a fixpoint. This resolves the solc dispatcher ladder, plain
`PUSH target JUMP`, and single-call-site internal-function returns
(the return address is pushed by the caller and survives the join).

Anything the dataflow cannot pin (calldata-derived targets, multi-site
internal returns whose join conflicts) marks the jump — and the CFG —
UNRESOLVED. Consumers degrade soundly: an unresolved CFG means "every
opcode in the code is reachable" (see effects.CodeSummary), never a
refined claim.

Soundness note on linear-sweep alignment: EVM jumpdest validity is
computed by the same linear sweep execution uses (bytes inside PUSH
operands are never valid jump targets), so every pc the engine can
execute appears in `Disassembly.instruction_list` — block-level
reasoning over that list covers all executable code of the object.
"""

from typing import Dict, List, Optional, Tuple

from mythril_tpu.support.opcodes import BY_NAME

# opcodes that end a basic block with no fall-through
HALTING_OPS = frozenset(
    {"STOP", "RETURN", "REVERT", "SELFDESTRUCT", "INVALID"}
)
# deepest abstract stack tracked per block (EVM's limit is 1024; constants
# relevant to jump resolution live near the top)
STACK_TRACK_DEPTH = 48
# dataflow fixpoint bound. The join is monotone per top-aligned position
# (constant -> unknown happens at most once, lengths only shrink), so a
# block's input can change at most ~2 x STACK_TRACK_DEPTH times; each
# worklist entry corresponds to one such change (plus at most one stale
# duplicate already queued), so this cap sits far above the bound and
# should never be hit. If it IS hit, the whole recovery is declared
# failed (consumers degrade to "everything reachable"): silently skipping
# a propagation could leave a stale constant in a successor's input and
# resolve a jump to the wrong target, which would make gating unsound.
MAX_DATAFLOW_VISITS_PER_BLOCK = 16 * STACK_TRACK_DEPTH


class BasicBlock:
    __slots__ = ("start", "end", "instrs", "successors", "unresolved",
                 "halts")

    def __init__(self, instrs):
        self.instrs = instrs
        self.start = instrs[0].address
        self.end = instrs[-1].address
        # statically-resolved successor block start pcs
        self.successors: List[int] = []
        # ends in a JUMP/JUMPI whose target the dataflow could not pin
        self.unresolved = False
        self.halts = instrs[-1].opcode in HALTING_OPS

    def opcode_names(self) -> frozenset:
        return frozenset(i.opcode for i in self.instrs)

    def __repr__(self):
        return (f"<BasicBlock {self.start}..{self.end} "
                f"succ={self.successors}"
                f"{' UNRESOLVED' if self.unresolved else ''}>")


_UNKNOWN = None  # abstract stack entry: statically unknown value


def _join_stacks(a: Optional[list], b: list) -> Tuple[list, bool]:
    """Top-aligned join; returns (joined, changed_vs_a). Entries below the
    shallower stack's depth are dropped (reads past the tracked depth
    yield unknown anyway)."""
    if a is None:
        return list(b), True
    depth = min(len(a), len(b))
    joined = []
    for i in range(1, depth + 1):
        va, vb = a[-i], b[-i]
        joined.append(va if va == vb else _UNKNOWN)
    joined.reverse()
    return joined, joined != a


class ControlFlowGraph:
    """blocks: start pc -> BasicBlock; `resolved` is False when any block
    reachable from pc 0 ends in a jump the dataflow could not pin."""

    def __init__(self, disassembly):
        self.blocks: Dict[int, BasicBlock] = {}
        self.block_starts: List[int] = []
        self._block_of_pc: Dict[int, int] = {}
        self._next_block: Dict[int, Optional[int]] = {}
        self.resolved = False
        # the dataflow overran its fixpoint bound: no resolution claim
        # from this recovery may be trusted (degrade everywhere)
        self.recovery_failed = False
        # block starts the dataflow actually processed: a block OUTSIDE
        # this set kept its constructor defaults (successors=[],
        # unresolved=False) and must never support a bounded-cone claim —
        # the engine can still land there through an unresolved dynamic
        # jump elsewhere, and its real successors were never computed
        self._dataflow_visited: set = set()
        self.reachable_starts: frozenset = frozenset()
        self._build(disassembly)

    def block_at(self, pc: int) -> Optional[BasicBlock]:
        start = self._block_of_pc.get(pc)
        return self.blocks.get(start) if start is not None else None

    # -- construction --------------------------------------------------------

    def _build(self, disassembly) -> None:
        instrs = disassembly.instruction_list
        if not instrs:
            return
        valid_dests = disassembly.valid_jump_destinations

        leaders = {0}
        for i, ins in enumerate(instrs[:-1]):
            if ins.opcode in ("JUMP", "JUMPI") or ins.opcode in HALTING_OPS:
                leaders.add(i + 1)
        for i, ins in enumerate(instrs):
            if ins.opcode == "JUMPDEST":
                leaders.add(i)
        ordered = sorted(leaders)
        for idx, lead in enumerate(ordered):
            stop = ordered[idx + 1] if idx + 1 < len(ordered) else len(instrs)
            block = BasicBlock(instrs[lead:stop])
            self.blocks[block.start] = block
            for ins in block.instrs:
                self._block_of_pc[ins.address] = block.start
        self.block_starts = sorted(self.blocks)
        for idx, start in enumerate(self.block_starts):
            self._next_block[start] = (
                self.block_starts[idx + 1]
                if idx + 1 < len(self.block_starts) else None
            )

        self._solve_dataflow(valid_dests)
        self._compute_reachability()

    def _solve_dataflow(self, valid_dests) -> None:
        """Propagate abstract input stacks block-to-block to a fixpoint,
        resolving jump targets from the simulated stack at each exit."""
        entry = self.blocks.get(0)
        if entry is None:
            return
        in_stacks: Dict[int, Optional[list]] = {0: []}
        visits: Dict[int, int] = {}
        self._dataflow_visited.add(0)
        work = [0]
        while work:
            start = work.pop()
            visits[start] = visits.get(start, 0) + 1
            if visits[start] > MAX_DATAFLOW_VISITS_PER_BLOCK:
                # should be unreachable (see the bound's derivation above);
                # declaring the recovery failed is the only sound exit —
                # an unpropagated join may have left stale constants
                self.recovery_failed = True
                return
            block = self.blocks[start]
            out_stack, targets = self._transfer(
                block, list(in_stacks.get(start) or []), valid_dests)
            block.successors = []
            block.unresolved = False
            last = block.instrs[-1]
            if last.opcode == "JUMP":
                if targets is _UNRESOLVED_TARGET:
                    block.unresolved = True
                else:
                    block.successors.extend(targets)
            elif last.opcode == "JUMPI":
                if targets is _UNRESOLVED_TARGET:
                    block.unresolved = True
                else:
                    block.successors.extend(targets)
                fall = self._fallthrough(start)
                if fall is not None:
                    block.successors.append(fall)
            elif not block.halts:
                fall = self._fallthrough(start)
                if fall is not None:
                    block.successors.append(fall)
            for succ in block.successors:
                self._dataflow_visited.add(succ)
                joined, changed = _join_stacks(
                    in_stacks.get(succ), out_stack)
                if changed or succ not in in_stacks:
                    in_stacks[succ] = joined
                    work.append(succ)

    def _fallthrough(self, start: int) -> Optional[int]:
        return self._next_block.get(start)

    @staticmethod
    def _transfer(block: BasicBlock, stack: list, valid_dests):
        """Simulate the block over an abstract stack (entries: int or
        unknown). Returns (exit stack, jump targets) where targets is a
        list of resolved pcs for a trailing JUMP/JUMPI, the _UNRESOLVED
        sentinel when the target is unknown, or () otherwise."""

        def pop():
            return stack.pop() if stack else _UNKNOWN

        targets = ()
        for ins in block.instrs:
            name = ins.opcode
            if name.startswith("PUSH"):
                stack.append(ins.argument_int)  # None for symbolic operand
            elif name.startswith("DUP"):
                n = int(name[3:])
                stack.append(stack[-n] if len(stack) >= n else _UNKNOWN)
            elif name.startswith("SWAP"):
                n = int(name[4:])
                if len(stack) >= n + 1:
                    stack[-1], stack[-n - 1] = stack[-n - 1], stack[-1]
                else:
                    # part of the swapped pair is below the tracked window:
                    # both become unknown
                    if stack:
                        stack[-1] = _UNKNOWN
                    while len(stack) < n + 1:
                        stack.insert(0, _UNKNOWN)
                    stack[-n - 1] = _UNKNOWN
            elif name in ("JUMP", "JUMPI"):
                target = pop()
                if name == "JUMPI":
                    pop()  # condition
                if target is _UNKNOWN:
                    targets = _UNRESOLVED_TARGET
                elif target in valid_dests:
                    targets = [target]
                else:
                    targets = []  # static jump to an invalid dest: halts
            else:
                spec = BY_NAME.get(name)
                pops = spec.pops if spec else 0
                pushes = spec.pushes if spec else 0
                for _ in range(pops):
                    pop()
                stack.extend([_UNKNOWN] * pushes)
            if len(stack) > STACK_TRACK_DEPTH:
                del stack[: len(stack) - STACK_TRACK_DEPTH]
        return stack, targets

    def _compute_reachability(self) -> None:
        """BFS from pc 0; an unresolved jump in a reachable block poisons
        the whole recovery (resolved=False)."""
        if 0 not in self.blocks or self.recovery_failed:
            return
        seen = {0}
        work = [0]
        resolved = True
        while work:
            block = self.blocks[work.pop()]
            if block.unresolved:
                resolved = False
            for succ in block.successors:
                if succ not in seen and succ in self.blocks:
                    seen.add(succ)
                    work.append(succ)
        self.reachable_starts = frozenset(seen)
        self.resolved = resolved

    # -- queries -------------------------------------------------------------

    def forward_closure(self, start_pc: int) -> Optional[frozenset]:
        """Block starts reachable from the block containing `start_pc`
        (inclusive); None when the closure touches an unresolved jump OR
        a block the dataflow never processed (its successors are just the
        constructor default, not a result — trusting them would declare
        cones bounded that aren't) — the cone cannot be bounded
        statically."""
        origin = self._block_of_pc.get(start_pc)
        if origin is None or self.recovery_failed:
            return None
        seen = {origin}
        work = [origin]
        while work:
            start = work.pop()
            if start not in self._dataflow_visited:
                return None
            block = self.blocks[start]
            if block.unresolved:
                return None
            for succ in block.successors:
                if succ not in seen and succ in self.blocks:
                    seen.add(succ)
                    work.append(succ)
        return frozenset(seen)


_UNRESOLVED_TARGET = object()


def build_cfg(disassembly) -> ControlFlowGraph:
    return ControlFlowGraph(disassembly)

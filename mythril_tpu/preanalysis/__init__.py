"""Static bytecode pre-analysis — compiler-style passes ahead of LASER.

Runs once per code object before symbolic execution starts and feeds
three consumers (the TVM pattern of analysis/transform passes ahead of
lowering; every decision is counted in SolverStatistics):

  module gating     the module loader skips attaching DetectionModules
                    whose trigger opcodes are statically unreachable
                    (`modules_gated`). Only applied when the executed
                    code is fully known statically — runtime (non-create)
                    analysis, no dynamic loader, no CREATE in reach —
                    and NEVER on CFG-recovery failure: unresolved dynamic
                    jumps degrade soundly to "everything reachable".
  fork-prune hints  the engine's stochastic fork pruning skips the
                    feasibility solve for states whose remaining
                    transaction cone is provably inert — no state
                    effects, no detector hook opcodes, no pending
                    obligations (`queries_avoided`). Keeping a
                    possibly-unsat state is always findings-sound (every
                    issue is solver-confirmed); the static proof just
                    says the kept state cannot generate detector traffic
                    either.
  CNF preprocessing unit propagation + pure-literal elimination applied
                    to every blasted instance before fingerprinting and
                    router dispatch (smt/solver/frontend._prepare), and
                    connected-component splitting at the CDCL settle
                    (`cnf_units_propagated`, `cnf_pure_literals`,
                    `cnf_clauses_removed`, `cnf_components_split`).

`--no-preanalysis` (CLI) or MYTHRIL_TPU_PREANALYSIS=0 disables the whole
subsystem; MYTHRIL_TPU_PREANALYSIS=1 force-enables it over the flag.
"""

import logging
import os
from typing import FrozenSet, Optional

from mythril_tpu.preanalysis.effects import (  # noqa: F401 (public API)
    EFFECT_OPCODES,
    CodeSummary,
    FunctionEffects,
)

log = logging.getLogger(__name__)


def enabled() -> bool:
    """Master switch: env override first, then the --no-preanalysis flag."""
    env = os.environ.get("MYTHRIL_TPU_PREANALYSIS", "")
    if env in ("0", "off", "false"):
        return False
    if env in ("1", "on", "true"):
        return True
    from mythril_tpu.support.args import args

    return not getattr(args, "no_preanalysis", False)


# -- per-code summaries (cached on the Disassembly object) -------------------


def get_code_summary(disassembly) -> Optional[CodeSummary]:
    """CodeSummary for a Disassembly, computed once and cached on the
    object (code objects are immutable). None for empty or symbolic code
    (deploy-time-patched bytes make the static sweep unreliable)."""
    if disassembly is None:
        return None
    cached = getattr(disassembly, "_preanalysis_summary", _MISS)
    if cached is not _MISS:
        return cached
    summary = None
    from mythril_tpu import resilience

    try:
        if (isinstance(disassembly.bytecode, bytes) and disassembly.bytecode
                and not resilience.fuse_blown("preanalysis.summary")):
            from mythril_tpu.observe.tracer import span as trace_span

            resilience.maybe_inject("preanalysis.summary")
            with trace_span("preanalysis.summary", cat="analyze",
                            code_bytes=len(disassembly.bytecode)):
                summary = CodeSummary(disassembly)
    except Exception:
        # pre-analysis must never break an analysis: degrade to "no info"
        # (nothing gated, every module attaches — the registered
        # disable-action site preanalysis.summary; repeated faults blow
        # the session fuse so a deterministic fault stops re-firing)
        log.exception("preanalysis failed; continuing without summaries")
        resilience.note_stage_failure("preanalysis.summary")
        summary = None
    try:
        disassembly._preanalysis_summary = summary
    except AttributeError:
        pass
    return summary


# -- consumer 1: module gating -----------------------------------------------


def gating_opcodes(contract, dynloader=None) -> Optional[FrozenSet[str]]:
    """The statically-reachable opcode set usable for detector gating, or
    None when gating would be unsound / is disabled:

      - pre-analysis disabled
      - a dynamic loader is configured (other contracts' code can run)
      - creation-mode analysis (the installed runtime code is a run-time
        artifact; its opcode set is not statically known)
      - CFG recovery failed (unresolved dynamic jump: degrade to
        "everything reachable", gate nothing)
      - CREATE/CREATE2 reachable (deployed child code is unknowable)
    """
    if not enabled() or dynloader is not None:
        return None
    try:
        if contract.is_create_mode or not contract.code_bytes:
            return None
        summary = get_code_summary(contract.disassembly)
    except AttributeError:
        return None
    if summary is None or not summary.resolved:
        return None
    reachable = summary.reachable_opcodes
    if reachable & {"CREATE", "CREATE2"}:
        return None
    return reachable


# -- consumer 2: fork-prune hints --------------------------------------------


def _detector_interesting_opcodes() -> FrozenSet[str]:
    """Opcodes whose reachability makes a cone non-inert: state effects
    plus every registered detection module's TRIGGER opcodes (the opcodes
    a module needs executed to ever raise — or solve for — an issue).
    Computed once per process (the module registry is a singleton).

    Observer hooks (e.g. TxOrigin's JUMPI taint check) are deliberately
    NOT in this set: a state can ride pre-acquired taint into an
    observer hook inside an otherwise-inert cone and cost one wasted
    (UNSAT) confirmation solve — a bounded performance leak, never a
    finding, since every module's issue path is solver-confirmed and an
    unsat state confirms nothing."""
    global _interesting_cache
    if _interesting_cache is not None:
        return _interesting_cache
    from mythril_tpu.analysis.module import EntryPoint, ModuleLoader
    from mythril_tpu.analysis.module.util import module_trigger_opcodes

    ops = set(EFFECT_OPCODES)
    for module in ModuleLoader().get_detection_modules():
        if module.entry_point != EntryPoint.CALLBACK:
            continue
        if getattr(module, "symbolic_jump_only", False):
            # inert_at only holds over cones the CFG fully RESOLVED —
            # every jump target a push constant, so the engine sees
            # concrete (never symbolic) destinations and this module's
            # predicate can never pass inside the cone
            continue
        ops |= module_trigger_opcodes(module)
    _interesting_cache = frozenset(ops)
    return _interesting_cache


_interesting_cache: Optional[FrozenSet[str]] = None
_MISS = object()


def prune_check_skippable(global_state) -> bool:
    """True iff the stochastic fork-pruning feasibility solve for this
    state can be skipped (the state is KEPT unchecked — always
    findings-sound) without generating downstream solver traffic: the
    state is a top-level frame with no pending issue obligations, and
    every path from its pc to transaction end within its code object
    provably avoids state effects and detector hook opcodes."""
    if not enabled():
        return False
    stack = getattr(global_state, "transaction_stack", None)
    if not stack or len(stack) != 1 or stack[-1][1] is not None:
        return False  # inner frame: the caller's cone is not covered
    from mythril_tpu.analysis.issue_annotation import IssueAnnotation
    from mythril_tpu.analysis.potential_issues import (
        PotentialIssuesAnnotation,
    )

    for annotation in global_state.annotations:
        if isinstance(annotation, PotentialIssuesAnnotation) \
                and annotation.potential_issues:
            return False  # pending confirmations would solve at tx end
        if isinstance(annotation, IssueAnnotation):
            return False
    summary = get_code_summary(global_state.environment.code)
    if summary is None:
        return False
    return summary.inert_at(global_state.mstate.pc,
                            _detector_interesting_opcodes())


def reset_caches() -> None:
    """Testing hook: drop the process-wide interesting-opcode set (module
    registrations may differ between tests)."""
    global _interesting_cache
    _interesting_cache = None

"""Per-code effect summaries over the recovered CFG.

A CodeSummary answers two static questions consumers gate on:

  reachable_opcodes   which opcodes can execute at all, starting from
                      pc 0 (the entry of every message call and of the
                      creation frame). Exact over a RESOLVED CFG;
                      degrades to the linear-sweep opcode union when any
                      reachable jump is unresolved — still sound (the
                      engine can only execute pcs in instruction_list;
                      see cfg.py's alignment note), just unrefined.
  cone_opcodes(pc)    which opcodes the rest of the transaction can
                      execute from `pc` onward within this code object.
                      None when the forward cone touches an unresolved
                      jump (no static bound exists).

Per-function effect summaries project the dispatcher's selector map
(Disassembly.function_entries) through cone_opcodes and intersect with
EFFECT_OPCODES — the hint payload handed to the search strategies.
"""

from typing import Dict, Optional

from mythril_tpu.preanalysis.cfg import ControlFlowGraph, build_cfg

# opcodes whose execution mutates world state, moves value, or leaves the
# current code object (the "effects" of a function summary)
EFFECT_OPCODES = frozenset({
    "SSTORE", "TSTORE",
    "CALL", "CALLCODE", "DELEGATECALL", "STATICCALL",
    "CREATE", "CREATE2",
    "SELFDESTRUCT",
})

# environment reads detectors key on (origin/timestamp/number/etc.):
# tracked in summaries so consumers can reason about "reads predictable
# state" separately from "writes state"
ENV_READ_OPCODES = frozenset({
    "ORIGIN", "TIMESTAMP", "NUMBER", "DIFFICULTY", "PREVRANDAO",
    "COINBASE", "GASLIMIT", "BLOCKHASH", "BALANCE", "SELFBALANCE",
    "BLOBHASH", "BLOBBASEFEE", "BASEFEE",
})


class FunctionEffects:
    """Static summary of one dispatcher entry."""

    __slots__ = ("selector", "entry_pc", "effects", "env_reads", "bounded")

    def __init__(self, selector: str, entry_pc: int,
                 effects: frozenset, env_reads: frozenset, bounded: bool):
        self.selector = selector
        self.entry_pc = entry_pc
        self.effects = effects        # EFFECT_OPCODES seen in the cone
        self.env_reads = env_reads    # ENV_READ_OPCODES seen in the cone
        self.bounded = bounded        # False: cone hit an unresolved jump

    @property
    def effect_free(self) -> bool:
        return self.bounded and not self.effects

    def __repr__(self):
        return (f"<FunctionEffects 0x{self.selector} @{self.entry_pc} "
                f"effects={sorted(self.effects)} bounded={self.bounded}>")


class CodeSummary:
    """Static pre-analysis of ONE code object (a Disassembly)."""

    def __init__(self, disassembly):
        self.cfg: ControlFlowGraph = build_cfg(disassembly)
        instrs = disassembly.instruction_list
        self.linear_opcodes = frozenset(i.opcode for i in instrs)
        self.resolved = self.cfg.resolved
        if self.resolved:
            reachable = set()
            for start in self.cfg.reachable_starts:
                reachable |= self.cfg.blocks[start].opcode_names()
            self.reachable_opcodes = frozenset(reachable)
        else:
            self.reachable_opcodes = self.linear_opcodes
        self._cone_cache: Dict[int, Optional[frozenset]] = {}
        self.function_effects: Dict[str, FunctionEffects] = {
            selector: self._summarize_function(selector, entry_pc)
            for selector, entry_pc in disassembly.function_entries.items()
        }

    # -- cones ---------------------------------------------------------------

    def cone_opcodes(self, pc: int) -> Optional[frozenset]:
        """Opcodes executable from `pc` to the end of the transaction
        within this code object; None when statically unboundable."""
        block = self.cfg.block_at(pc)
        if block is None:
            return None
        cached = self._cone_cache.get(block.start, _MISS)
        if cached is not _MISS:
            return cached
        closure = self.cfg.forward_closure(block.start)
        if closure is None:
            cone = None
        else:
            ops = set()
            for start in closure:
                ops |= self.cfg.blocks[start].opcode_names()
            cone = frozenset(ops)
        self._cone_cache[block.start] = cone
        return cone

    def inert_at(self, pc: int, interesting: frozenset) -> bool:
        """True iff every path from `pc` to transaction end provably avoids
        all of `interesting` (conservative: unresolved cones are never
        inert)."""
        cone = self.cone_opcodes(pc)
        return cone is not None and not (cone & interesting)

    def _summarize_function(self, selector: str,
                            entry_pc: int) -> FunctionEffects:
        cone = self.cone_opcodes(entry_pc)
        if cone is None:
            # unbounded cone: assume every effect (sound default)
            return FunctionEffects(selector, entry_pc, EFFECT_OPCODES,
                                   ENV_READ_OPCODES, bounded=False)
        return FunctionEffects(
            selector, entry_pc,
            cone & EFFECT_OPCODES, cone & ENV_READ_OPCODES, bounded=True)


_MISS = object()

"""Cube-and-conquer split-variable selection over packed cones.

Classic cube-and-conquer splits a hard SAT instance on a few carefully
chosen variables into 2^k cubes (one per sign pattern) that are solved
independently; the lookahead literature picks split variables by how
much of the instance each one touches. Here the selection reuses the
variable-incidence view the PR-4 partitioning passes introduced
(preanalysis/components.py builds connectivity from exactly these
variable-gate edges): a PackedCircuit's per-var gate tables ga_var /
gb_var ARE the variable->gate incidence of the cone, so degree
centrality — how many gates read an input variable directly — is one
numpy bincount, no graph library needed. High-fanout inputs (selector
bytes, the callvalue word's low bits) gate the most downstream
structure, so pinning them both splits the search space evenly and
shortens every justification walk that would otherwise re-derive them.

The cubes ride the device as extra asserted roots on a ragged stream
(tpu/circuit.RaggedStream `extra_roots`): every cube is the ORIGINAL
cone plus pinned input literals, so any model the kernel finds for any
cube is a model of the original query — soundness needs no new
machinery. The device cannot refute: cubes that come back modelless are
candidate refutations only, and the host CDCL remains the per-cube
fallback and the sole UNSAT oracle (the standard crosscheck policy).
"""

from typing import List, Sequence, Tuple

import numpy as np

Cube = List[Tuple[int, bool]]  # [(local input var, pinned value), ...]


def select_cube_vars(pc, k: int) -> List[int]:
    """The top-`k` cone INPUT variables by degree centrality in the
    variable-gate incidence graph (direct fanout: gates whose fanin
    tables name the variable). Deterministic: ties break toward the
    lower variable id, so repeated dispatches cube identically."""
    if k <= 0 or not getattr(pc, "ok", False):
        return []
    fanout = (np.bincount(pc.ga_var, minlength=pc.v1)
              + np.bincount(pc.gb_var, minlength=pc.v1))
    is_input = pc.is_gate == 0
    is_input[0] = False  # the shared constant is not splittable
    candidates = np.nonzero(is_input & (fanout > 0))[0]
    if candidates.size == 0:
        return []
    order = np.lexsort((candidates, -fanout[candidates]))
    return [int(v) for v in candidates[order][:k]]


def enumerate_cubes(split_vars: Sequence[int]) -> List[Cube]:
    """All 2^k sign patterns over `split_vars` — the cube set. Empty
    selection yields no cubes (the caller keeps the un-split cone)."""
    if not split_vars:
        return []
    cubes: List[Cube] = []
    for pattern in range(1 << len(split_vars)):
        cubes.append([(var, bool((pattern >> i) & 1))
                      for i, var in enumerate(split_vars)])
    return cubes


def plan_cubes(pc, cube_vars: int, max_cubes: int) -> List[Cube]:
    """Cube plan for one packed cone, bounded by `max_cubes` (the
    caller's memory/variable budget for replicating the cone onto a
    ragged stream): the split width shrinks until 2^k fits, and a
    budget under 2 cubes means the cone ships un-split."""
    if max_cubes < 2:
        return []
    k = min(int(cube_vars), max(int(max_cubes), 1).bit_length() - 1)
    return enumerate_cubes(select_cube_vars(pc, k))

"""Variable-disjoint partition of an optimized AIG with per-component
root projection.

An instance rewritten by aig_opt is a fresh AIG holding EXACTLY the live
cone of its asserted roots, so partitioning is a single native
connectivity pass over its gate table (no cone re-extraction): two roots
share a component iff their cones are connected through shared gates or
inputs. Each component carries its own projected root set and lazily
materializes its own dense-renumbered CNF sub-instance (aig.to_cnf over
the projected roots — the same exporter the monolith uses), so:

  - the device router dispatches eligible components INDIVIDUALLY
    (level-bucketed like whole queries) while oversized siblings settle
    on the host CDCL — a deep monolith with small independent sub-cones
    no longer forfeits the device path (closes the ROADMAP item);
  - the persistent solve-result tier fingerprints components separately,
    so a sub-cone shared by different parent queries hits across them;
  - components whose every root is an input literal (the unit roots the
    sweep emits for pinned inputs) are trivial: their model is their
    literals, no solver of any kind needed.

Partitioning applies ONLY to AIGs carrying the `_aig_opt_cone` marker:
the shared global blaster AIG holds every cone ever blasted and walking
it per query would be both wrong (foreign cones) and unaffordable.
"""

from collections import OrderedDict
from typing import Dict, List, Optional

import numpy as np

from mythril_tpu.smt.bitblast import AIG

_CACHE_MAX = 256
_NOT_APPLICABLE = object()
_cache: "OrderedDict" = OrderedDict()

# a partition only pays when the router can do something with it; past
# this many components the instance is pathological and the bookkeeping
# (per-component CNF + fingerprints) would dominate
MAX_COMPONENTS = 512


class AIGComponent:
    """One variable-disjoint sub-cone of an optimized instance."""

    __slots__ = ("roots", "trivial_assignment", "_instance")

    def __init__(self, roots: List[int], trivial_assignment):
        self.roots = roots  # projected root literals (optimized numbering)
        # {aig var: bool} when every root is an input literal (no gates):
        # the component's model IS its literals — solved inline, no
        # dispatch, no CDCL. None for components with real structure.
        self.trivial_assignment = trivial_assignment
        self._instance = None  # lazy (num_vars, cnf, dense) sub-instance

    def instance(self, aig: AIG):
        """The component's own blasted sub-instance: dense variable remap
        + CNF over just this component's cone (cached — sibling queries
        and repeated dispatches share one emission)."""
        if self._instance is None:
            self._instance = aig.to_cnf(list(self.roots))
        return self._instance


class AIGPartition:
    __slots__ = ("aig", "components")

    def __init__(self, aig: AIG, components: List[AIGComponent]):
        self.aig = aig
        self.components = components


def _cone_vars(lhs, rhs, root_vars) -> np.ndarray:
    """Vars in the cone of `root_vars` over the numpy gate arrays (cones
    are bounded by aig_opt's AIG_OPT_NODE_CAP upstream)."""
    seen = set()
    stack = [v for v in root_vars if v]
    while stack:
        var = stack.pop()
        if var in seen:
            continue
        seen.add(var)
        a = int(lhs[var])
        if a >= 0:
            if a >> 1:
                stack.append(a >> 1)
            b = int(rhs[var])
            if b >> 1:
                stack.append(b >> 1)
    return np.fromiter(seen, dtype=np.int64, count=len(seen))


def partition_roots(aig: AIG, roots: List[int]) -> Optional[AIGPartition]:
    """Partition an optimized AIG's roots into variable-disjoint
    components; None when not applicable (unmarked AIG, scipy missing,
    single component, constant roots, or a pathological component
    count).

    Connectivity is computed over the CONE of this root set, not the
    whole gate table: the session strash AIG (aig_opt._StrashSession)
    accumulates every sibling query's rewrite, so a whole-graph pass
    would both cost O(session) per query and glue THIS query's disjoint
    components together through foreign gates that merely share their
    inputs."""
    if not getattr(aig, "_aig_opt_cone", False):
        return None
    root_vars = [lit >> 1 for lit in roots]
    if not root_vars or any(v == 0 for v in root_vars):
        return None  # constant roots: the monolith path handles them
    from mythril_tpu.preanalysis.components import connected_labels

    lhs, rhs = aig.gate_arrays()
    cone = np.sort(_cone_vars(lhs, rhs, root_vars))
    gate_vars = cone[lhs[cone] >= 0]
    edges_u = np.concatenate([gate_vars, gate_vars])
    edges_v = np.concatenate(
        [lhs[gate_vars] >> 1, rhs[gate_vars] >> 1])
    keep = edges_v != 0  # constant fanins do not connect components
    # compact node space: every kept endpoint is a cone member (cones are
    # closed under fanin), so searchsorted is an exact index
    labels = connected_labels(
        len(cone),
        np.searchsorted(cone, edges_u[keep]),
        np.searchsorted(cone, edges_v[keep]))
    if labels is None:
        return None
    root_idx = np.searchsorted(cone, np.asarray(root_vars, dtype=np.int64))
    groups: Dict[int, List[int]] = {}
    for lit, idx in zip(roots, root_idx):
        groups.setdefault(int(labels[idx]), []).append(lit)
    if len(groups) < 2 or len(groups) > MAX_COMPONENTS:
        return None

    is_gate = lhs >= 0
    components: List[AIGComponent] = []
    for label in sorted(groups):
        comp_roots = groups[label]
        trivial = None
        if all(not is_gate[lit >> 1] for lit in comp_roots):
            trivial = {}
            for lit in comp_roots:
                var, value = lit >> 1, not (lit & 1)
                if trivial.get(var, value) != value:
                    trivial = None  # contradictory units: let a solver say
                    break
                trivial[var] = value
        components.append(AIGComponent(comp_roots, trivial))
    return AIGPartition(aig, components)


def partition_for_aig_roots(aig_roots) -> Optional[AIGPartition]:
    """The single gate both consumers (the router's component dispatch
    and the disk tier's component assembly) use to decide whether a
    prepared instance's (aig, roots, dense) triple is partitioned: the
    AIG must carry the aig_opt rewrite marker, the triple must carry a
    dense map, and any failure degrades to None (monolithic handling) —
    one implementation, so the two seams can never disagree."""
    try:
        aig = aig_roots[0]
    except (TypeError, IndexError, KeyError):
        return None
    if not getattr(aig, "_aig_opt_cone", False):
        return None
    try:
        if len(aig_roots) < 3 or aig_roots[2] is None:
            return None
        return partition_cached(aig, aig_roots[1])
    except Exception:
        return None  # partitioning must never break a solve


def component_vars(component_dense):
    """The component's global (optimized-AIG) vars — the iteration space
    for merging its sub-model into the parent query's bit space. Derived
    from the dense map (not a PackedCircuit): it exists for every
    component, including cones past the device compile caps."""
    import numpy as np

    return np.nonzero(component_dense.arr)[0]


def partition_cached(aig: AIG, roots) -> Optional[AIGPartition]:
    key = (getattr(aig, "uid", id(aig)), tuple(roots))
    hit = _cache.get(key)
    if hit is not None:
        _cache.move_to_end(key)
        return None if hit is _NOT_APPLICABLE else hit
    result = partition_roots(aig, list(roots))
    _cache[key] = _NOT_APPLICABLE if result is None else result
    while len(_cache) > _CACHE_MAX:
        _cache.popitem(last=False)
    return result


def merge_component_bits(component_dense, query_dense, var_map,
                         component_bits, merged: List[bool]) -> None:
    """Copy one solved component's model bits into the full query's bit
    space: component-dense -> global (optimized-AIG) var -> query-dense.
    `var_map` is the component PackedCircuit's local->global map (or an
    iterable of the component's global vars)."""
    for gvar in var_map:
        if gvar == 0:
            continue
        cvar = component_dense.get(gvar)
        qvar = query_dense.get(gvar)
        if cvar is not None and qvar is not None and qvar < len(merged):
            merged[qvar] = bool(component_bits[cvar])


def apply_trivial_assignment(component: AIGComponent, query_dense,
                             merged: List[bool]) -> bool:
    """Write a trivial component's pinned literals into the query's bit
    space; False when the component is not trivial."""
    if component.trivial_assignment is None:
        return False
    for var, value in component.trivial_assignment.items():
        qvar = query_dense.get(var)
        if qvar is not None and qvar < len(merged):
            merged[qvar] = value
    return True


def reset_cache() -> None:
    """Testing hook."""
    _cache.clear()

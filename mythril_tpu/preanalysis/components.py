"""Shared native connectivity pass for the static partitioning passes.

Both variable-disjointness splits in the preanalysis layer — the CNF
connected-component split at the host-CDCL settle (cnf_prep.py) and the
AIG-level partition with per-component root projection (aig_partition.py)
— reduce to connected components of a sparse incidence graph. scipy's
csgraph pass is native and runs in ~1 ms at the split caps, where a
Python union-find measured 150+ ms — too expensive for a decision that
usually answers "one component, no split". This module is the single
implementation both callers share.
"""

from typing import Optional

import numpy as np


def connected_labels(num_nodes: int, edges_u, edges_v) -> Optional[np.ndarray]:
    """Component label per node of an undirected graph given as parallel
    edge-endpoint arrays. Returns None when scipy is unavailable (callers
    degrade to "no split") or the graph is empty."""
    if num_nodes <= 0:
        return None
    try:
        import scipy.sparse as sparse
        from scipy.sparse.csgraph import connected_components
    except ImportError:
        return None  # no native connectivity pass: splitting not worth it
    edges_u = np.asarray(edges_u, dtype=np.int64)
    edges_v = np.asarray(edges_v, dtype=np.int64)
    graph = sparse.coo_matrix(
        (np.ones(len(edges_u), dtype=np.int8), (edges_u, edges_v)),
        shape=(num_nodes, num_nodes))
    _count, labels = connected_components(graph, directed=False)
    return labels

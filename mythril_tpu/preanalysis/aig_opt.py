"""AIG structural analysis & rewriting ahead of CNF emission.

The blasted AIG the device circuit kernel simulates — and the CNF every
solver path consumes — is produced by construction-time folding only:
the shared global blaster strashes gates as they are BUILT, but nothing
ever re-analyzes a finished cone. Asserted roots carry exploitable
static structure: a root is a literal that must be TRUE, so root
conjunction trees decompose into forced fanin literals, forced literals
pin circuit inputs, and pinned inputs collapse the arithmetic cones that
share them (selector bytes pinning a comparison chain is the canonical
case). This module runs once per prepared instance (the TVM pattern of
graph-level rewriting ahead of device codegen) and rewrites the cone
into a fresh minimized AIG:

  constant sweep   every root literal is a forced constant; forced TRUE
                   AND gates decompose into forced fanins to a fixpoint,
                   forced values substitute as structural constants at
                   every use site, and dead fanout cones are never
                   rebuilt. A root forced both ways is a statically
                   proven UNSAT — counted, but the verdict still settles
                   through the CDCL so the detection-path crosscheck
                   policy is never bypassed (the rewrite emits a
                   one-variable contradiction the CDCL re-derives in
                   microseconds).
  strashing        the rebuild re-hashes every surviving gate through a
                   SESSION structural-hash table shared across sibling
                   queries (_StrashSession): gates that became identical
                   under the swept constants merge (the build-time strash
                   cannot see these: the originals differed structurally
                   when they were created), and gates a sibling query
                   already swept/strashed are reused literal-for-literal
                   instead of rebuilding against a fresh table (counted
                   strash_xquery_merges; a per-gate rewrite memo
                   short-circuits whole forced-constant-free sub-cones).
                   Double negations cancel on the literal encoding.

Soundness: the rewrite is equisatisfiable with a recorded reconstruction
map (`input_map`, original input var -> rewritten var): swept inputs are
kept as variables pinned by unit roots, so every model of the rewritten
instance assigns them and Solver._reconstruct — which validates every
model against the ORIGINAL word-level constraints — accepts the
recomposed assignment unchanged. Inputs whose every use folded away are
genuine don't-cares and take the reconstruction default (False).

Everything here is total: any unexpected shape degrades to "no change"
(None), never to a wrong cone. Gated by `--no-aig-opt` /
MYTHRIL_TPU_AIG_OPT on top of the preanalysis master switch.
"""

import contextlib
import os
import threading
from collections import OrderedDict
from typing import Dict, List, Optional

from mythril_tpu.smt.bitblast import AIG, FALSE_LIT, TRUE_LIT

# cones past this many variables skip the rewrite: the sweep is a few
# linear Python passes over the cone, and cones this size are dominated
# by CDCL/device wall anyway (the CNF preprocessor has the same shape of
# cap for the same reason)
AIG_OPT_NODE_CAP = 150_000

_CACHE_MAX = 256
_NOT_APPLICABLE = object()
# (global aig uid, roots tuple) -> AIGOptResult | _NOT_APPLICABLE. Sound
# key: the shared blaster AIG is append-only, so a root literal's cone
# never changes once created. Caching matters doubly here: sibling
# analyze queries re-blast into memoized terms (same roots), and the
# cached result's session AIG keeps a stable uid so the device backend's
# pack/pad caches keep hitting across calls.
_cache: "OrderedDict" = OrderedDict()


def _cache_max() -> int:
    """Result-cache entry cap, env-overridable for long corpus runs with
    many distinct sibling root sets (MYTHRIL_TPU_AIG_CACHE_MAX)."""
    try:
        return max(1, int(os.environ["MYTHRIL_TPU_AIG_CACHE_MAX"]))
    except (KeyError, ValueError):
        return _CACHE_MAX


# rewrites accumulate in ONE session AIG per source AIG; past this many
# variables the session resets (bounds memory, mirrors BLASTER_VAR_CAP)
SESSION_VAR_CAP = 4_000_000


class _StrashSession:
    """Session strash/rewrite table shared across sibling queries.

    Every cone rewritten from the same source AIG rebuilds into ONE
    shared append-only session AIG, so the strash table — and a per-gate
    rewrite memo for gates whose fanin cone carries no query-specific
    forced constant — persist across sibling queries: a sub-cone swept
    and strashed by query N is reused literal-for-literal by query N+1
    (counted `strash_xquery_merges`), instead of each cone rewriting
    against a fresh table (the PR-4 ROADMAP follow-on this closes).

    Sound because the source AIG is append-only (an original var's gate
    never changes) and `input_vars`/`clean_memo` key on original vars:
    a memo entry is only consulted when the current query proves the
    gate's whole fanin cone forced-constant-free (`clean` tracking in
    optimize_roots), which is exactly the condition under which the
    rebuild is query-independent. A new source AIG uid (term-generation
    bump rebuilds the global blaster) or the var cap retires the session;
    results cached against a retired session stay valid — they hold
    their own reference to its (still append-only) AIG."""

    __slots__ = ("source_uid", "aig", "input_vars", "clean_memo")

    def __init__(self, source_uid):
        self.source_uid = source_uid
        self.aig = AIG()
        self.aig._aig_opt_cone = True  # partition-eligible (aig_partition)
        self.input_vars: Dict[int, int] = {}   # source var -> session var
        self.clean_memo: Dict[int, int] = {}   # source gate var -> session lit


_session: Optional[_StrashSession] = None


def _get_session(aig: AIG) -> _StrashSession:
    global _session
    uid = getattr(aig, "uid", id(aig))
    from mythril_tpu.smt.solver import incremental

    if not incremental.enabled():
        # cross-query sharing rides the incremental-prep switch: with the
        # layer off every rewrite gets a private throwaway table (the
        # pre-session per-query behavior), so the bench on/off legs
        # isolate the whole layer
        return _StrashSession(uid)
    if (_session is None or _session.source_uid != uid
            or _session.aig.num_vars > SESSION_VAR_CAP):
        _session = _StrashSession(uid)
    return _session


# -- root-forcing-deferred sweep (fork bundles) ------------------------------
#
# The sides of one batched-JUMPI fork pair share every base constraint
# and differ by exactly the fork literal and its negation. The normal
# sweep FORCES every root — so side A rewrites its shared base cone
# under "fork literal = TRUE" and side B under "= FALSE", the rebuilt
# base roots diverge structurally, and the router's shared-cone pair
# packing (_pack_fork_pair: "root sets differ by exactly {L, L^1}")
# misses on exactly the traffic it was built for. Inside this scope the
# sweep DEFERS root forcing entirely: the cone rebuilds through the
# session strash table with every root kept as a plain root, so both
# sides land in ONE session AIG with identical base roots (the second
# side's rebuild is all clean-memo hits) and the diff is the fork
# literal pair — the CDCL re-derives the forced constants by unit
# propagation in microseconds, which is why deferring is cheap.
# Threadlocal because serve batches hop runner threads.

_defer_tls = threading.local()


@contextlib.contextmanager
def deferred_forcing():
    """Prepare-scope marker for fork-bundle queries: optimize_roots runs
    with root forcing deferred (see block comment above)."""
    depth = getattr(_defer_tls, "depth", 0)
    _defer_tls.depth = depth + 1
    try:
        yield
    finally:
        _defer_tls.depth = depth


def defer_active() -> bool:
    """Deferred-forcing scope armed AND not disabled by env
    (MYTHRIL_TPU_FORK_DEFER_SWEEP=0 restores the per-side forced sweep
    — the bench on/off comparison for the pair-packing hit rate)."""
    if not getattr(_defer_tls, "depth", 0):
        return False
    return os.environ.get("MYTHRIL_TPU_FORK_DEFER_SWEEP", "") \
        not in ("0", "off", "false")


def _cone_gate_count(aig: AIG, roots) -> int:
    """Gates in the cone of `roots` — the session AIG holds every sibling
    query's rewrite, so per-instance node counts must be cone-local."""
    gate_lhs, gate_rhs = aig.gate_lhs, aig.gate_rhs
    seen = set()
    count = 0
    stack = [lit >> 1 for lit in roots if (lit >> 1) != 0]
    while stack:
        var = stack.pop()
        if var in seen:
            continue
        seen.add(var)
        lhs = gate_lhs[var]
        if lhs >= 0:
            count += 1
            if (lhs >> 1) != 0:
                stack.append(lhs >> 1)
            rhs = gate_rhs[var]
            if (rhs >> 1) != 0:
                stack.append(rhs >> 1)
    return count


def enabled() -> bool:
    """The AIG layer rides the preanalysis subsystem: it is on by default
    whenever preanalysis is, `--no-aig-opt` turns just this layer off, and
    MYTHRIL_TPU_AIG_OPT=0/1 overrides the flag either way (the preanalysis
    master switch still gates everything)."""
    from mythril_tpu import preanalysis

    if not preanalysis.enabled():
        return False
    env = os.environ.get("MYTHRIL_TPU_AIG_OPT", "")
    if env in ("0", "off", "false"):
        return False
    if env in ("1", "on", "true"):
        return True
    from mythril_tpu.support.args import args

    return not getattr(args, "no_aig_opt", False)


class ComposedDense:
    """Original global AIG var -> dense CNF var of the REWRITTEN instance,
    composed through the rewrite's input map. Drop-in for the DenseMap
    protocol Solver._reconstruct consumes; gate vars and dropped inputs
    resolve to None (reconstruction's standard outside-the-cone default)."""

    __slots__ = ("input_map", "dense")

    def __init__(self, input_map: Dict[int, int], dense):
        self.input_map = input_map
        self.dense = dense

    def get(self, var: int, default=None):
        new_var = self.input_map.get(var)
        if new_var is None:
            return default
        return self.dense.get(new_var, default)


class AIGOptResult:
    __slots__ = ("aig", "roots", "input_map", "nodes_before", "nodes_after",
                 "strash_merges", "const_folds", "trivially_unsat",
                 "xquery_merges")

    def __init__(self, aig, roots, input_map, nodes_before, nodes_after,
                 strash_merges, const_folds, trivially_unsat,
                 xquery_merges=0):
        self.aig = aig                # shared session AIG (cone of roots)
        self.roots = roots            # root literals in the new numbering
        self.input_map = input_map    # orig input var -> new var
        self.nodes_before = nodes_before
        self.nodes_after = nodes_after
        self.strash_merges = strash_merges
        self.const_folds = const_folds
        self.trivially_unsat = trivially_unsat
        # gates reused from SIBLING queries via the session strash table
        self.xquery_merges = xquery_merges


def _trivially_unsat_result(nodes_before: int, const_folds: int,
                            strash_merges: int = 0) -> AIGOptResult:
    """A statically proven UNSAT root set rewrites to a one-variable
    contradiction: two unit roots the CDCL refutes by propagation in
    microseconds — through the normal solve path, so the detection-path
    UNSAT crosscheck policy applies exactly as it would have (a static
    verdict must never silently bypass that soundness net)."""
    new_aig = AIG()
    var = new_aig.new_var()
    new_aig._aig_opt_cone = True
    return AIGOptResult(new_aig, [2 * var, 2 * var + 1], {},
                        nodes_before, 0, strash_merges, const_folds,
                        trivially_unsat=True)


def optimize_roots(aig: AIG, roots: List[int],
                   force_roots: bool = True) -> Optional[AIGOptResult]:
    """Rewrite the cone of `roots` (sweep + strash); None when nothing
    applies (constant-only roots, oversize cone, or any unexpected shape
    — always degrade to "no change", never a wrong cone).

    With `force_roots=False` (fork-bundle queries under
    deferred_forcing) the constant sweep is DEFERRED: no root is
    propagated as a forced constant — the cone rebuilds through the
    session strash table with every root kept as a plain root, so the
    two sides of a fork pair produce identical base roots in one shared
    session AIG and the router's shared-cone pair packing hits. The
    rewrite is returned even when structurally unchanged: symmetry
    between the sides is the point (one side rewritten and the other
    degraded to the original AIG could never pair)."""
    live_roots = []
    for lit in roots:
        if lit == TRUE_LIT:
            continue  # vacuous root
        if lit == FALSE_LIT:
            return _trivially_unsat_result(0, 0)
        live_roots.append(lit)
    if not live_roots:
        return None

    gate_lhs, gate_rhs = aig.gate_lhs, aig.gate_rhs

    # -- cone of influence (ascending var ids ARE topological order: the
    #    append-only AIG creates every gate after its fanins) ---------------
    in_cone = set()
    stack = [lit >> 1 for lit in live_roots if (lit >> 1) != 0]
    while stack:
        var = stack.pop()
        if var in in_cone:
            continue
        in_cone.add(var)
        if len(in_cone) > AIG_OPT_NODE_CAP:
            return None
        lhs = gate_lhs[var]
        if lhs >= 0:
            if (lhs >> 1) != 0:
                stack.append(lhs >> 1)
            rhs = gate_rhs[var]
            if (rhs >> 1) != 0:
                stack.append(rhs >> 1)
    if not in_cone:
        return None
    cone_vars = sorted(in_cone)
    nodes_before = sum(1 for v in cone_vars if gate_lhs[v] >= 0)

    if not force_roots:
        from mythril_tpu.smt.solver import incremental

        if not incremental.enabled():
            # without the shared session each side would rebuild into a
            # private throwaway AIG — the sides could never pair. Keep
            # the ORIGINAL aig/roots (both sides share the source AIG,
            # so the pair still packs there).
            return None

    # -- constant sweep, backward half: decompose forced-TRUE AND gates
    #    (skipped wholesale when root forcing is deferred) -----------------
    forced: Dict[int, bool] = {}
    queue = list(live_roots) if force_roots else []
    while queue:
        lit = queue.pop()
        if lit == TRUE_LIT:
            continue
        if lit == FALSE_LIT:
            return _trivially_unsat_result(nodes_before, len(forced) + 1)
        var, value = lit >> 1, not (lit & 1)
        known = forced.get(var)
        if known is not None:
            if known != value:
                return _trivially_unsat_result(nodes_before,
                                               len(forced) + 1)
            continue
        forced[var] = value
        if value and gate_lhs[var] >= 0:
            # the gate output must be 1 => both fanin literals must be 1
            queue.append(gate_lhs[var])
            queue.append(gate_rhs[var])

    # -- liveness, backward half: only structure reachable from the gates
    #    that stay asserted (forced-FALSE gates) is ever rebuilt — the
    #    decomposed conjunction skeleton and dead fanout cones are pruned --
    if force_roots:
        live_struct = set()
        for var in reversed(cone_vars):
            is_gate = gate_lhs[var] >= 0
            needs_structure = var in live_struct or (
                is_gate and forced.get(var) is False)
            if not needs_structure or not is_gate:
                continue
            live_struct.add(var)
            for child_lit in (gate_lhs[var], gate_rhs[var]):
                child = child_lit >> 1
                if child != 0 and child not in forced:
                    live_struct.add(child)
    else:
        # no forcing: every root stays asserted structurally, so the
        # whole cone of influence is live
        live_struct = set(cone_vars)

    # -- rebuild (forward): substitute forced constants at every use site,
    #    re-hash surviving gates through the SESSION strash table — gates
    #    a sibling query already rebuilt merge instead of rebuilding, and
    #    forced-free ("clean") sub-cones short-circuit through the
    #    per-gate rewrite memo ----------------------------------------------
    session = _get_session(aig)
    new_aig = session.aig
    session_start = new_aig.num_vars  # watermark: older vars = sibling work
    new_lit: Dict[int, int] = {0: FALSE_LIT}
    for var, value in forced.items():
        new_lit[var] = TRUE_LIT if value else FALSE_LIT
    input_map: Dict[int, int] = {}
    new_roots: List[int] = []
    strash_merges = 0
    xquery_merges = 0
    rebuild_folds = 0
    trivially_unsat = False
    # var -> True iff no var in its fanin cone is forced THIS query: the
    # exact condition under which its rebuild is query-independent and the
    # session clean_memo may serve (or store) it
    clean: Dict[int, bool] = {}

    def _sub(lit: int) -> int:
        return new_lit[lit >> 1] ^ (lit & 1)

    def _session_input(var: int) -> int:
        new_var = session.input_vars.get(var)
        if new_var is None:
            new_var = new_aig.new_var()
            session.input_vars[var] = new_var
        return new_var

    def _rebuild_gate(var: int) -> int:
        nonlocal strash_merges, rebuild_folds, xquery_merges
        a, b = _sub(gate_lhs[var]), _sub(gate_rhs[var])
        before = new_aig.num_vars
        lit = new_aig.and_gate(a, b)
        if new_aig.num_vars == before:
            if lit in (TRUE_LIT, FALSE_LIT) or (lit >> 1) in (a >> 1, b >> 1):
                rebuild_folds += 1  # collapsed by a swept constant/absorption
            elif (lit >> 1) <= session_start:
                xquery_merges += 1  # strash hit on a sibling query's gate
            else:
                strash_merges += 1  # merged with an already-rebuilt gate
        return lit

    for var in cone_vars:
        is_gate = gate_lhs[var] >= 0
        value = forced.get(var)
        if value is not None and not is_gate:
            # pinned input: keep it as a variable pinned by a unit root so
            # reconstruction (and stored-bit replay) still sees its value;
            # its uses were substituted as structural constants above.
            # Session-shared: sibling queries pinning the same input (to
            # either polarity) assert units over ONE session variable.
            new_var = _session_input(var)
            input_map[var] = new_var
            new_roots.append(2 * new_var + (0 if value else 1))
            continue
        if value is False and is_gate:
            # asserted-false gate: its structure stays asserted (rebuilt
            # with substituted fanins); its fanout uses the constant
            rebuilt = _rebuild_gate(var)
            asserted = rebuilt ^ 1
            if asserted == FALSE_LIT:
                trivially_unsat = True
                break
            if asserted != TRUE_LIT:  # TRUE = tautology under the sweep
                new_roots.append(asserted)
            continue
        if value is not None:
            continue  # forced-TRUE gate: fully decomposed, nothing to keep
        if var not in live_struct:
            continue  # dead fanout: pruned
        if not is_gate:
            new_var = _session_input(var)
            input_map[var] = new_var
            new_lit[var] = 2 * new_var
            clean[var] = True
            continue
        lhs_var, rhs_var = gate_lhs[var] >> 1, gate_rhs[var] >> 1
        pure = (clean.get(lhs_var, lhs_var == 0)
                and clean.get(rhs_var, rhs_var == 0))
        if pure:
            hit = session.clean_memo.get(var)
            if hit is not None:
                new_lit[var] = hit
                clean[var] = True
                xquery_merges += 1
                continue
        new_lit[var] = _rebuild_gate(var)
        if pure:
            clean[var] = True
            session.clean_memo[var] = new_lit[var]

    const_folds = len(forced) + rebuild_folds
    if trivially_unsat:
        return _trivially_unsat_result(nodes_before, const_folds,
                                       strash_merges)
    if not force_roots:
        # deferred forcing: roots were not decomposed, so they emit by
        # direct literal mapping — the rebuilt cone's image of each
        # original root, polarity preserved
        for lit in live_roots:
            mapped = new_lit.get(lit >> 1)
            if mapped is None:
                return None  # unexpected shape: degrade to "no change"
            mapped ^= lit & 1
            if mapped == FALSE_LIT:
                return _trivially_unsat_result(nodes_before, const_folds,
                                               strash_merges)
            if mapped == TRUE_LIT:
                continue
            new_roots.append(mapped)
    new_roots = list(dict.fromkeys(new_roots))
    # cone-local count: the session AIG also holds sibling queries' cones
    nodes_after = _cone_gate_count(new_aig, new_roots)
    unchanged = force_roots and (
        nodes_after >= nodes_before
        and strash_merges == 0
        and rebuild_folds == 0
        and len(new_roots) == len(live_roots)
        and not any(gate_lhs[v] < 0 for v in forced)  # no pinned inputs
    )
    if unchanged:
        # the rebuild reproduced the cone one-to-one. Usually that means
        # "keep the original" (re-emitting an identical instance would
        # only churn numbering) — EXCEPT when the cone is variable-
        # disjoint: the rewritten AIG is what makes per-component root
        # projection possible downstream, so a splittable identity
        # rewrite is still worth keeping.
        from mythril_tpu.preanalysis import aig_partition

        if aig_partition.partition_roots(new_aig, new_roots) is None:
            return None
    return AIGOptResult(new_aig, new_roots, input_map, nodes_before,
                        nodes_after, strash_merges, const_folds,
                        trivially_unsat=False, xquery_merges=xquery_merges)


def optimize_roots_cached(aig: AIG, roots: List[int]) \
        -> Optional[AIGOptResult]:
    # fork-bundle queries (deferred_forcing scope) run the root-forcing-
    # deferred sweep; the flag is part of the cache key — the same root
    # set prepared outside a fork bundle must never serve (or be served
    # by) the unforced rewrite
    force_roots = not defer_active()
    key = (getattr(aig, "uid", id(aig)), tuple(roots), force_roots)
    hit = _cache.get(key)
    if hit is not None:
        _cache.move_to_end(key)
        return None if hit is _NOT_APPLICABLE else hit
    result = optimize_roots(aig, roots, force_roots=force_roots)
    _cache[key] = _NOT_APPLICABLE if result is None else result
    cache_max = _cache_max()
    while len(_cache) > cache_max:
        _cache.popitem(last=False)
    return result


def evaluate_roots(aig: AIG, roots: List[int],
                   input_values: Dict[int, bool]) -> bool:
    """Simulate the cone under a total input assignment (missing inputs
    default False) and report whether every root literal holds — the
    reference evaluator the semantic-preservation property tests compare
    the rewritten cone against."""
    values: Dict[int, bool] = {0: False}
    gate_lhs, gate_rhs = aig.gate_lhs, aig.gate_rhs

    def lit_value(lit: int) -> bool:
        return values[lit >> 1] ^ bool(lit & 1)

    for var in range(1, aig.num_vars + 1):
        if gate_lhs[var] >= 0:
            values[var] = lit_value(gate_lhs[var]) and lit_value(gate_rhs[var])
        else:
            values[var] = bool(input_values.get(var, False))
    return all(lit_value(r) for r in roots)


def reset_cache() -> None:
    """Drop the result cache AND the session strash table (clear_caches /
    testing hook) — stale-generation entries must never resolve against a
    rebuilt term graph."""
    global _session
    _cache.clear()
    _session = None

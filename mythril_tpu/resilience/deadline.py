"""Hard deadline wrapper for the device ship/kernel seam.

The router's dispatch budget bounds how long the kernel LOOP runs — but
only if control ever comes back from the backend. A wedged transport
(the axon tunnel that has hung bench rounds for 120s at a time, per
ROADMAP) blocks INSIDE a jax call with no Python-level preemption point,
and the whole analysis hangs with it. The only sound rescue without
killing the process is to run the device call on a separate thread and
abandon it at the deadline: the query proceeds on the host CDCL, the
stage breaker opens, and the wedged call either finishes late into a
discarded result or stays stuck in its daemon thread until exit.

One PERSISTENT runner thread (not thread-per-call): device work keeps a
stable thread identity across dispatches (jit caches, XLA client state),
and the steady-state cost per call is one queue round-trip. When a call
times out the runner is marked wedged and abandoned — the next admitted
call (the breaker's half-open probe, typically) gets a fresh runner with
fresh queues, so a late result from the wedged thread can never be
mistaken for the new call's.
"""

import logging
import queue
import threading
from typing import Callable, Optional

log = logging.getLogger(__name__)


class StageDeadlineExceeded(RuntimeError):
    """The wrapped call did not return within its hard deadline."""


class _Runner:
    def __init__(self):
        self._inbox: "queue.Queue" = queue.Queue()
        self._outbox: "queue.Queue" = queue.Queue()
        self.wedged = False
        self._thread = threading.Thread(
            target=self._loop, name="mythril-tpu-stage-runner", daemon=True)
        self._thread.start()

    def _loop(self) -> None:
        while True:
            fn = self._inbox.get()
            if fn is None:
                return
            try:
                self._outbox.put((True, fn()))
            except BaseException as error:  # delivered to the caller
                self._outbox.put((False, error))

    def call(self, fn: Callable, deadline_s: float):
        self._inbox.put(fn)
        try:
            ok, payload = self._outbox.get(timeout=deadline_s)
        except queue.Empty:
            self.wedged = True
            raise StageDeadlineExceeded(
                f"stage call exceeded its {deadline_s:.1f}s hard deadline")
        if ok:
            return payload
        raise payload


def new_runner() -> _Runner:
    """A DEDICATED runner for a caller that must not share the
    module-global one: the serve daemon runs whole request batches under
    a deadline, and those batches themselves cross run_with_deadline for
    device dispatch — on a shared runner the inner call would queue
    behind the batch occupying the only runner thread and self-deadlock
    into a spurious device deadline. The caller owns wedged-replacement
    (check `.wedged`, drop the runner, call new_runner() again)."""
    return _Runner()


_runner: Optional[_Runner] = None
_runner_lock = threading.Lock()


def _get_runner() -> _Runner:
    global _runner
    with _runner_lock:
        if _runner is None or _runner.wedged:
            _runner = _Runner()
        return _runner


def run_with_deadline(site: str, fn: Callable, deadline_s: float):
    """Run `fn` under a hard deadline. On timeout: counts a `deadline`
    resilience event for `site` and raises StageDeadlineExceeded — the
    caller degrades to its sound path and feeds its breaker a hard
    failure. Exceptions from `fn` propagate unchanged. A non-positive
    deadline means no bound (inline call)."""
    if deadline_s is None or deadline_s <= 0:
        return fn()
    try:
        runner = _get_runner()
    except Exception:  # cannot thread: run inline, unguarded
        return fn()
    try:
        return runner.call(fn, deadline_s)
    except StageDeadlineExceeded:
        from mythril_tpu.resilience import record_event

        record_event(site, "deadline")
        log.warning("%s exceeded its %.1fs hard deadline: abandoning the "
                    "call (wedged backend?); the sound path takes over",
                    site, deadline_s)
        raise


def reset() -> None:
    """Testing hook: drop the runner (a wedged one is abandoned)."""
    global _runner
    with _runner_lock:
        if _runner is not None and not _runner.wedged:
            _runner._inbox.put(None)
        _runner = None

"""Per-stage circuit breaker with half-open re-probe.

Generalizes the router's zero-hit waste breaker (PR 1): that breaker
tracked one failure signal (device seconds without a model) and, once
tripped, disabled the device path for the REST OF THE RUN. For a
long-lived analyzer-as-a-service process that is the wrong terminal
state — a transient wedge (tunnel hiccup, OOM-killed sibling) would
permanently cost the fast path. The standard serving-stack answer is the
three-state breaker:

  closed     stage runs normally; failures accumulate (count + wasted
             seconds against an optional waste budget).
  open       stage is off; every allow() is refused until the cooldown
             elapses. A HARD failure (deadline trip: wedged backend)
             opens immediately regardless of counts.
  half-open  after the cooldown, exactly ONE probe is admitted. Success
             closes the breaker (meters reset); failure re-opens it for
             another cooldown.

All transitions are counted into SolverStatistics (breaker_trip /
breaker_probe events per site) and the stats JSON resilience section, so
a run report shows WHEN a stage was lost and whether it came back.
"""

import logging
import os
import time

log = logging.getLogger(__name__)

COOLDOWN_ENV = "MYTHRIL_TPU_BREAKER_COOLDOWN"
DEFAULT_COOLDOWN_S = 30.0
DEFAULT_FAILURE_THRESHOLD = 3

CLOSED, OPEN, HALF_OPEN = "closed", "open", "half_open"


def _count(site: str, event: str) -> None:
    from mythril_tpu.resilience import record_event

    record_event(site, event)


class StageBreaker:
    """One breaker per registered stage; the owning stage consults
    allow() before running and reports record_success/record_failure."""

    def __init__(self, site: str, failure_threshold: int =
                 DEFAULT_FAILURE_THRESHOLD,
                 waste_budget_s: float = 0.0,
                 cooldown_s: float = 0.0):
        self.site = site
        self.failure_threshold = failure_threshold
        # 0 = no waste budget (count-threshold only); the router passes
        # its MYTHRIL_TPU_DEVICE_MAX_WASTE budget here
        self.waste_budget_s = waste_budget_s
        if cooldown_s <= 0:
            from mythril_tpu.support.env import env_float

            cooldown_s = env_float(COOLDOWN_ENV, DEFAULT_COOLDOWN_S)
        self.cooldown_s = cooldown_s
        self.state = CLOSED
        self.failures = 0
        self.waste_s = 0.0
        self.trips = 0
        self._reopen_at = 0.0
        self._probe_admitted_at = 0.0

    # -- queries --------------------------------------------------------------

    def allow(self) -> bool:
        """May the stage run now? Open breakers refuse until the cooldown
        elapses, then admit exactly one half-open probe. An admitted probe
        that never reports an outcome (the caller was admitted but found
        no eligible work to dispatch — e.g. every query in the window was
        filtered before the device call) EXPIRES after another cooldown
        and a new probe is admitted, so an outcome-less admission can
        never leave the stage off for good."""
        if self.state == CLOSED:
            return True
        now = time.monotonic()
        if self.state == OPEN and now >= self._reopen_at:
            self.state = HALF_OPEN
            self._probe_admitted_at = now
            _count(self.site, "breaker_probe")
            log.info("%s breaker half-open: admitting one re-probe",
                     self.site)
            return True
        if self.state == HALF_OPEN \
                and now - self._probe_admitted_at >= self.cooldown_s:
            self._probe_admitted_at = now
            _count(self.site, "breaker_probe")
            log.info("%s breaker: outstanding re-probe reported no "
                     "outcome for %.0fs; admitting a fresh one",
                     self.site, self.cooldown_s)
            return True
        # open and cooling down, or a half-open probe already in flight
        return False

    @property
    def tripped(self) -> bool:
        """True while the stage is off (open and still cooling down, or
        a half-open probe in flight)."""
        return self.state != CLOSED

    # -- transitions ----------------------------------------------------------

    def record_success(self) -> None:
        if self.state != CLOSED:
            log.info("%s breaker closed: re-probe succeeded", self.site)
        self.state = CLOSED
        self.failures = 0
        self.waste_s = 0.0

    def record_failure(self, wasted_s: float = 0.0, hard: bool = False,
                       count: bool = True) -> None:
        """One stage failure. `wasted_s` charges the waste budget (the
        router's fruitless device seconds); `hard` trips immediately
        (deadline exceeded: the backend is wedged, not slow);
        count=False charges ONLY the waste budget — a zero-hit device
        dispatch is a legitimate outcome (the CDCL settles it), not an
        error, so it must never reach the count threshold on a healthy
        fast device."""
        self.waste_s += wasted_s
        if count:
            self.failures += 1
        if self.state == HALF_OPEN and (count or hard):
            # only a real ERROR re-opens a probe immediately; a clean
            # zero-hit probe (count=False) is a legitimate outcome on an
            # UNSAT-heavy stretch — it stays half-open (one dispatch per
            # cooldown) and re-trips only through the waste budget below,
            # which _trip resets, so the budget meters the window SINCE
            # the last trip rather than instantly re-tripping forever
            self._trip("re-probe failed")
            return
        if hard:
            self._trip("hard failure")
            return
        if count and self.failures >= self.failure_threshold:
            self._trip(f"{self.failures} consecutive failures")
            return
        if self.waste_budget_s and self.waste_s > self.waste_budget_s:
            self._trip(f"{self.waste_s:.1f}s wasted "
                       f"(budget {self.waste_budget_s:.1f}s)")

    def force_open(self, reason: str = "forced") -> None:
        """Administrative trip (e.g. backend unavailable at startup)."""
        if self.state != OPEN:
            self._trip(reason)

    def _trip(self, reason: str) -> None:
        self.state = OPEN
        self.trips += 1
        # meters measure the window since the last trip: without the
        # reset, a breaker opened on waste would re-trip on the first
        # half-open probe's epsilon of new waste, terminally
        self.failures = 0
        self.waste_s = 0.0
        self._reopen_at = time.monotonic() + self.cooldown_s
        _count(self.site, "breaker_trip")
        log.warning("%s breaker OPEN (%s): degrading to the sound path "
                    "for %.0fs, then one re-probe", self.site, reason,
                    self.cooldown_s)

    def reset(self) -> None:
        self.state = CLOSED
        self.failures = 0
        self.waste_s = 0.0
        self._reopen_at = 0.0

"""Deterministic fault-injection harness.

Armed by `MYTHRIL_TPU_FAULTS` (or `--inject-fault`), a comma-separated
list of plans:

    MYTHRIL_TPU_FAULTS=<site>:<kind>:<trigger>[,<site>:<kind>:<trigger>...]

  site     a registered fault site name (registry.FAULT_SITES)
  kind     raise | hang | delay | corrupt | exit (registry.KINDS)
  trigger  n<k>   fire exactly once, on the k-th crossing of the site
           r<p>   fire each crossing with probability p (seeded RNG —
                  MYTHRIL_TPU_FAULT_SEED, default 0 — so a given seed
                  reproduces the same fault schedule bit-for-bit)
           *      fire on every crossing (the deterministic-fault shape)

Example: MYTHRIL_TPU_FAULTS=device.dispatch:raise:n1,disk.entry:corrupt:*

Design constraints:
  disabled cost  maybe_inject() with no spec configured is one module-
                 global load and a truthiness check — guarded under the
                 tracer's 2%-of-stress-wall budget by tier-1
                 (tests/test_resilience.py).
  determinism    per-site crossing counters + a per-site seeded RNG: the
                 same spec and seed produce the same fault schedule in
                 every run, which is what lets the chaos suite assert
                 byte-identical findings.
  containment    every injected fault surfaces as InjectedFault (or a
                 sleep / byte mangle / process exit) AT a registered
                 site, inside that site's existing degradation scope —
                 the harness tests the handlers, it never adds new
                 failure modes outside them.
"""

import logging
import os
import random
import zlib
from typing import Dict, Optional

from mythril_tpu.resilience import registry

log = logging.getLogger(__name__)

FAULTS_ENV = "MYTHRIL_TPU_FAULTS"
SEED_ENV = "MYTHRIL_TPU_FAULT_SEED"

# how long a "hang" blocks: far past any stage deadline, so the deadline
# wrapper (deadline.py) is what ends it — never the sleep itself
HANG_SECONDS = 600.0
DELAY_SECONDS = 0.05


class InjectedFault(RuntimeError):
    """Raised by an armed `raise` plan at its site."""


class _Plan:
    __slots__ = ("site", "kind", "mode", "value", "crossings", "fired")

    def __init__(self, site: str, kind: str, mode: str, value: float):
        self.site = site
        self.kind = kind
        self.mode = mode       # "nth" | "rate" | "always"
        self.value = value     # k for nth, p for rate
        self.crossings = 0
        self.fired = 0


# site -> _Plan; None = harness disarmed (THE hot-path check)
_plans: Optional[Dict[str, _Plan]] = None
_rngs: Dict[str, random.Random] = {}
_spec: str = ""


def parse_spec(spec: str) -> Dict[str, _Plan]:
    """Parse a fault spec; unknown sites/kinds/triggers raise ValueError
    (a mistyped chaos spec silently injecting nothing would make every
    chaos assertion vacuous)."""
    plans: Dict[str, _Plan] = {}
    for part in spec.split(","):
        part = part.strip()
        if not part:
            continue
        pieces = part.split(":")
        if len(pieces) != 3:
            raise ValueError(f"fault plan {part!r}: want site:kind:trigger")
        site, kind, trigger = pieces
        if site not in registry.FAULT_SITES:
            raise ValueError(f"fault plan {part!r}: unknown site {site!r}")
        if site in plans:
            raise ValueError(
                f"fault plan {part!r}: site {site!r} already has a plan — "
                "a silently dropped duplicate would make its chaos "
                "assertions vacuous")
        if kind not in registry.FAULT_SITES[site].kinds:
            raise ValueError(
                f"fault plan {part!r}: kind {kind!r} not meaningful at "
                f"{site} (supported: {registry.FAULT_SITES[site].kinds})")
        if trigger == "*":
            plans[site] = _Plan(site, kind, "always", 0.0)
        elif trigger.startswith("n"):
            plans[site] = _Plan(site, kind, "nth", int(trigger[1:]))
        elif trigger.startswith("r"):
            plans[site] = _Plan(site, kind, "rate", float(trigger[1:]))
        else:
            raise ValueError(
                f"fault plan {part!r}: trigger must be n<k>, r<p> or *")
    return plans


def configure(spec: Optional[str]) -> None:
    """(Re)arm the harness from an explicit spec string, or disarm with
    None/empty. Resets every crossing counter and RNG — each configure
    starts a fresh, reproducible fault schedule."""
    global _plans, _spec
    _rngs.clear()
    if not spec:
        _plans = None
        _spec = ""
        return
    _plans = parse_spec(spec)
    _spec = spec
    seed = int(os.environ.get(SEED_ENV, "0") or "0")
    for site in _plans:
        _rngs[site] = random.Random(seed ^ zlib.crc32(site.encode()))
    log.warning("fault injection ARMED: %s (seed %d)", spec, seed)


def configure_from_env(cli_spec: Optional[str] = None) -> None:
    """Arm from MYTHRIL_TPU_FAULTS, falling back to the --inject-fault
    CLI value. Called at analyzer start (core.fire_lasers) and in every
    --jobs worker, so both read one consistent schedule source."""
    configure(os.environ.get(FAULTS_ENV) or cli_spec)


def active_spec() -> str:
    """The armed spec string ('' when disarmed) — stats JSON provenance."""
    return _spec


def _should_fire(plan: _Plan) -> bool:
    plan.crossings += 1
    if plan.mode == "always":
        return True
    if plan.mode == "nth":
        return plan.crossings == plan.value
    return _rngs[plan.site].random() < plan.value


def _count_injected(site: str) -> None:
    # lazy import: this module is imported by the package __init__
    from mythril_tpu.resilience import record_event

    record_event(site, "injected")


def maybe_inject(site: str) -> None:
    """Crossing hook placed at every registered fault site. No-op unless
    a plan for `site` is armed and its trigger fires; then raises
    InjectedFault / sleeps / exits per the plan kind. `corrupt` plans do
    nothing here — they act through corrupt_text() on the site's data
    path instead."""
    if _plans is None:
        return
    plan = _plans.get(site)
    # corrupt plans act only through corrupt_text() on the site's data
    # path — consuming a crossing here would shift (or swallow) the n-th
    # trigger the data-path hook is waiting for
    if plan is None or plan.kind == "corrupt" or not _should_fire(plan):
        return
    plan.fired += 1
    _count_injected(site)
    if plan.kind == "raise":
        raise InjectedFault(f"injected fault at {site} "
                            f"(crossing {plan.crossings})")
    if plan.kind == "hang":
        import time

        log.warning("injected hang at %s (deadline wrapper must rescue)",
                    site)
        time.sleep(HANG_SECONDS)
        return
    if plan.kind == "delay":
        import time

        time.sleep(DELAY_SECONDS)
        return
    if plan.kind == "exit":
        log.warning("injected process exit at %s", site)
        os._exit(86)
    # "corrupt": only meaningful on the data path (corrupt_text)


def corrupt_text(site: str, text: str) -> str:
    """Data-path hook for `corrupt` plans: mangle `text` when the site's
    corrupt plan fires (deterministic truncate-and-garbage — exercises
    the torn-write / bad-blob shapes a real disk fault produces)."""
    if _plans is None:
        return text
    plan = _plans.get(site)
    if plan is None or plan.kind != "corrupt" or not _should_fire(plan):
        return text
    plan.fired += 1
    _count_injected(site)
    return text[: len(text) // 2] + "\x00CORRUPTED"

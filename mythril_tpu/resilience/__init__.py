"""Pipeline-wide fault containment.

Every acceleration layer in this repo is an optional fast path over a
correct oracle (device kernel over the host CDCL, disk tier over a real
solve, batched frontier over the per-state interpreter, incremental
prepare over the full pipeline, --jobs workers over in-process
execution). This package makes the failure handling of those layers a
typed, tested property instead of an ad-hoc collection of excepts:

  registry.py   the fault-site registry: each optional stage declares
                ONE site and ONE sound-degradation action
                (retry / breaker / quarantine / disable)
  breaker.py    per-stage circuit breaker with half-open re-probe
                (generalizes the router's zero-hit waste breaker)
  deadline.py   hard deadline wrapper for the device ship/kernel seam
                (a wedged backend trips the breaker instead of hanging
                the query)
  faults.py     deterministic injection harness
                (MYTHRIL_TPU_FAULTS=<site>:<kind>:<trigger>,... /
                --inject-fault), driving the chaos suite
                (tests/test_chaos.py) whose invariant is: under every
                injected fault class, analysis completes with findings
                byte-identical to the no-fault run

plus, here: session FUSES for the disable-for-session action, and the
jittered-retry helper for the retry action. Every event (retry, trip,
probe, quarantine, degradation, deadline, requeue, stale lock break,
injection) flows into SolverStatistics, the stats JSON `resilience`
section, and the span tracer as tagged zero-width spans.
"""

import logging
import random
import time
import zlib
from typing import Callable, Dict

from mythril_tpu.resilience import registry  # noqa: F401 (public API)
from mythril_tpu.resilience.breaker import StageBreaker  # noqa: F401
from mythril_tpu.resilience.deadline import (  # noqa: F401
    StageDeadlineExceeded,
    run_with_deadline,
)
from mythril_tpu.resilience.faults import (  # noqa: F401
    InjectedFault,
    corrupt_text,
    maybe_inject,
)

log = logging.getLogger(__name__)

# failures of a disable-action stage before its session fuse blows: a
# transient hiccup costs one degraded event; a DETERMINISTIC fault (same
# exception every query) reaches the threshold within a few queries and
# the stage stays off for the session instead of failing-and-degrading
# thousands of times
FUSE_THRESHOLD = 3

_fuse_failures: Dict[str, int] = {}
_fuses_blown: Dict[str, bool] = {}


def record_event(site: str, event: str, count: int = 1) -> None:
    """Count one resilience event (SolverStatistics + stats JSON
    `resilience` section) and mark it on the span timeline as a
    zero-width tagged event. Flight-recorder trigger events
    (breaker_trip / deadline) then auto-dump the ring of recent spans as
    a post-mortem artifact — AFTER the event itself entered the ring, so
    the dump contains its own trigger."""
    from mythril_tpu.observe import flightrec
    from mythril_tpu.observe.tracer import span as trace_span
    from mythril_tpu.smt.solver.statistics import SolverStatistics

    SolverStatistics().add_resilience_event(site, event, count)
    with trace_span("resilience." + event, cat="resilience", site=site):
        pass
    flightrec.notify(site, event)


def note_stage_failure(site: str, hard: bool = False) -> bool:
    """One failure of a disable-action stage: counts a `degraded` event
    and charges the session fuse (hard=True blows it immediately).
    Returns True when the fuse just blew."""
    record_event(site, "degraded")
    if _fuses_blown.get(site):
        return False
    failures = _fuse_failures.get(site, 0) + 1
    _fuse_failures[site] = failures
    if hard or failures >= FUSE_THRESHOLD:
        _fuses_blown[site] = True
        log.warning(
            "%s disabled for the rest of the session after %d failure(s): "
            "%s", site, failures,
            registry.FAULT_SITES[site].degrades_to
            if site in registry.FAULT_SITES else "sound path takes over")
        return True
    return False


def fuse_blown(site: str) -> bool:
    """Is this disable-action stage off for the session?"""
    return _fuses_blown.get(site, False)


def with_retries(site: str, fn: Callable, attempts: int = 2,
                 base_delay_s: float = 0.002):
    """Run `fn`, retrying transient failures with seeded jittered
    backoff (deterministic under the fault harness — the jitter RNG
    seeds on the site name + pid, so two contending workers draw
    DIFFERENT jitter and desynchronize instead of retrying in
    lockstep). Each retry counts a `retry` event; the final failure
    propagates for the caller to degrade."""
    import os

    rng = random.Random(zlib.crc32(site.encode()) ^ os.getpid())
    for attempt in range(attempts):
        try:
            return fn()
        except Exception:
            if attempt + 1 >= attempts:
                raise
            record_event(site, "retry")
            time.sleep(base_delay_s * (2 ** attempt) * (1 + rng.random()))


def reset_session() -> None:
    """Drop session fuses and failure counts (clear_caches/tests)."""
    _fuse_failures.clear()
    _fuses_blown.clear()

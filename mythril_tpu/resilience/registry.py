"""Typed fault-domain registry.

Every OPTIONAL stage of the pipeline — each acceleration layer built in
front of a correct-but-slower oracle — registers exactly one fault site
here, with a DECLARED sound-degradation action. The registry is static
data on purpose: the lint (tools/check_fault_sites.py) walks it and
fails tier-1 when a site lacks a degradation action, lacks a chaos test,
or is registered but never wired into the code, so "we handle failures
there" can never again be an undocumented claim.

Degradation actions (the vocabulary of the tentpole):

  retry       transient device/IO faults: retry with jittered backoff
              (seeded — reproducible under the fault harness), then
              degrade. Used for disk writes, lock acquisition, coalesced
              flushes (per-query isolation retry), and --jobs worker
              death (requeue the dead worker's pending inputs once).
  breaker     per-stage circuit breaker (breaker.py): repeated or hard
              failures open the stage; after a cooldown a single
              half-open probe may re-close it. Generalizes the router's
              zero-hit waste breaker.
  quarantine  corrupt/unverifiable cache entries: the entry file is
              moved aside (never re-read, preserved for forensics) and
              the lookup proceeds as a safe miss — the oracle recomputes.
  disable     deterministic faults in a pure-optimization layer: the
              layer is disabled for the rest of the session (fuse in
              __init__.py) and the sound full pipeline runs instead.

Every degradation lands on the sound path: the host CDCL, the full
prepare pipeline, the per-state interpreter, in-process execution, or a
cache miss. None of them can change findings — that is the chaos-suite
invariant (tests/test_chaos.py).
"""

from typing import Dict, NamedTuple, Tuple

ACTIONS = ("retry", "breaker", "quarantine", "disable")

# injection kinds the harness understands (faults.py):
#   raise    raise InjectedFault at the site
#   hang     block at the site (the deadline wrapper must rescue)
#   delay    short sleep (transient-fault shape for retry sites)
#   corrupt  mangle bytes flowing through the site (cache entries)
#   exit     kill the process (worker-death shape; --jobs workers only)
KINDS = ("raise", "hang", "delay", "corrupt", "exit")


class FaultSite(NamedTuple):
    name: str
    layer: str            # subsystem the site lives in
    action: str           # declared degradation action (ACTIONS)
    kinds: Tuple[str, ...]  # injection kinds meaningful at this site
    degrades_to: str      # the sound path a failure lands on


FAULT_SITES: Dict[str, FaultSite] = {
    site.name: site
    for site in (
        FaultSite(
            "device.dispatch", "tpu/router", "breaker",
            ("raise", "hang"),
            "host CDCL settles the batch; breaker opens on waste/"
            "deadline, half-open re-probe after cooldown"),
        FaultSite(
            "device.calibrate", "tpu/router", "disable",
            ("raise",),
            "uncalibrated defaults for the session (raised static caps)"),
        FaultSite(
            "disk.entry", "service/store", "quarantine",
            ("corrupt", "raise"),
            "entry quarantined, lookup degrades to a safe miss "
            "(counted persistent_verify_rejects)"),
        FaultSite(
            "disk.write", "service/store", "retry",
            ("raise", "delay"),
            "one jittered-backoff retry, then the verdict simply is not "
            "persisted (reads re-solve)"),
        FaultSite(
            "store.lock", "support/lock", "retry",
            ("raise",),
            "stale locks broken (owner-pid liveness + max-age); a broken "
            "lock layer degrades to unlocked atomic-rename writes"),
        FaultSite(
            "scheduler.flush", "service/scheduler", "retry",
            ("raise",),
            "failed window flush retries each buffered query "
            "individually; only a query that fails alone degrades to "
            "unknown (possibly-feasible)"),
        FaultSite(
            "prepare.incremental", "smt/solver/incremental", "disable",
            ("raise",),
            "full (non-resumed) prepare pipeline; repeated faults blow "
            "the session fuse"),
        FaultSite(
            "aig.session", "preanalysis/aig_opt", "disable",
            ("raise",),
            "identity rewrite (un-optimized cone); repeated faults blow "
            "the session fuse"),
        FaultSite(
            "frontier.step", "laser/frontier", "disable",
            ("raise",),
            "per-state interpreter steps the states; repeated faults "
            "blow the session fuse"),
        FaultSite(
            "preanalysis.summary", "preanalysis", "disable",
            ("raise",),
            "no static summary: nothing is gated, every module attaches "
            "(the pre-PR-3 behavior, always findings-sound)"),
        FaultSite(
            "jobs.worker", "core", "retry",
            ("raise", "exit"),
            "dead worker's pending contracts requeued into a fresh pool "
            "once, then analyzed in-process"),
        FaultSite(
            "serve.request", "serve/daemon", "quarantine",
            ("raise",),
            "the poisoned request alone answers `error`; sibling "
            "tenants' requests in the same batch complete with findings "
            "untouched"),
        FaultSite(
            "serve.admission", "serve/daemon", "disable",
            ("raise",),
            "fair tenant round-robin admission degrades to plain FIFO "
            "ordering (session fuse after repeated faults); nothing is "
            "dropped, only ordered"),
        FaultSite(
            "serve.worker", "serve/daemon", "retry",
            ("raise", "hang"),
            "wedged worker batch deadline-killed on a dedicated runner "
            "thread (the abandoned body cancels at its next check); its "
            "requests requeue into a fresh batch once, then answer "
            "`incomplete` — never hung, siblings' results kept"),
        FaultSite(
            "fleet.shard", "fleet/supervisor", "retry",
            ("raise",),
            "a dead or faulted shard's in-flight request re-routes once "
            "to a surviving shard, then answers `incomplete`; the "
            "supervisor crash-only restarts the shard, which re-warms "
            "from the shared network tier"),
        FaultSite(
            "fleet.route", "fleet/router", "disable",
            ("raise",),
            "digest-keyed rendezvous routing degrades to round-robin "
            "shard placement for the session (fuse after repeated "
            "faults); requests still land on a live shard, only warm-"
            "tier affinity is lost"),
        FaultSite(
            "netstore.entry", "fleet/netstore", "quarantine",
            ("corrupt", "raise"),
            "corrupt shared-tier entry quarantined on the READING "
            "shard, whose lookup degrades to a safe miss and re-solves; "
            "the writing shard is untouched (counted "
            "net_tier_verify_rejects)"),
    )
}


def validate() -> None:
    """Structural sanity of the registry itself (called by the lint)."""
    for name, site in FAULT_SITES.items():
        assert name == site.name, f"registry key {name!r} != {site.name!r}"
        assert site.action in ACTIONS, \
            f"fault site {name}: unknown action {site.action!r}"
        assert site.kinds, f"fault site {name}: no injection kinds"
        for kind in site.kinds:
            assert kind in KINDS, \
                f"fault site {name}: unknown injection kind {kind!r}"
        assert site.degrades_to, \
            f"fault site {name}: no degradation description"
